"""Unified disk-pressure governance (DESIGN.md §26).

Every durable surface in the stack — the exec cache, the warm-state
cache, rotating checkpoint snapshots, journal segments — historically
assumed infinite disk: an ENOSPC anywhere was an unhandled OSError that
killed the process mid-write. This module is the single byte-budget
authority they all consult:

- **one budget** (`configure(budget_bytes=...)`) bounds the governed
  artifact pool; `checkpoint.prune_warm_cache` reads it first, before
  the `PRIMETPU_CACHE_MAX_BYTES` env var, so `--cache-budget` is one
  knob over the whole warm+exec cache tree;
- **preflight** (`preflight(path, need_bytes, kind)`) is called inside
  `checkpoint.atomic_save_npz` and `journal.JobJournal.append` BEFORE
  bytes hit the disk. When free space (or a chaos-injected ENOSPC
  window) cannot cover the write, it runs the retry ladder;
- **the ladder** is priority-ordered eviction — registered evictors run
  cheapest-to-recreate first (caches at priority 0, rotated snapshots
  at priority 1; ACKed journal state is NEVER an evictor) — then
  registered compactors (journal snapshot+truncate), and only when both
  fail does it raise the typed `DiskPressureError` carrying a
  `retry_after_s` hint, which the serve protocol surfaces as admission
  backpressure exactly like `QueueFull`/`ReplicaQuorumLost`. Disk-full
  degrades service; it does not crash it.

The chaos `capacity_loss` class drives the `disk.preflight` site
(sites.disk_full): a plan event opens a sustained window during which
preflight sees zero free bytes no matter what the real filesystem says,
so the ladder — and the no-ACKed-job-lost invariant G — is exercised on
a healthy container.
"""

from __future__ import annotations

import os
import shutil

from ..chaos import sites as chaos

#: free-bytes floor kept on the filesystem beyond the write itself —
#: a write that would leave less than this headroom is treated as
#: pressure even before the kernel says ENOSPC
DEFAULT_HEADROOM_BYTES = 8 << 20

_BUDGET: int | None = None
_HEADROOM: int = DEFAULT_HEADROOM_BYTES

# name -> (priority, fn); fn(need_bytes) -> freed bytes (best effort,
# may return 0 — the ladder rechecks real free space after every rung)
_EVICTORS: dict[str, tuple[int, object]] = {}
# name -> fn; fn() -> None (journal compaction and friends)
_COMPACTORS: dict[str, object] = {}

_IN_LADDER = False  # reentrancy guard: ladder work may itself write

stats = {
    "preflights": 0,
    "pressure_events": 0,
    "evictions_run": 0,
    "compactions_run": 0,
    "rejections": 0,
}


class DiskPressureError(OSError):
    """Typed admission backpressure for a disk that stayed full after
    the whole evict -> compact ladder ran. Carries the `retry_after_s`
    hint the serve protocol returns to clients (the same shape as
    `QueueFull`/`ReplicaQuorumLost`), so a full disk sheds load instead
    of killing the daemon."""

    def __init__(self, detail: str, *, path: str | None = None,
                 need_bytes: int = 0, retry_after_s: float = 2.0):
        super().__init__(detail)
        self.path = path
        self.need_bytes = int(need_bytes)
        self.retry_after_s = float(retry_after_s)

    def location(self) -> dict:
        loc: dict = {"need_bytes": self.need_bytes}
        if self.path is not None:
            loc["path"] = self.path
        return loc


def configure(budget_bytes: int | None = None,
              headroom_bytes: int | None = None) -> None:
    """Set the process-wide governed byte budget (None = env/default)
    and optionally the free-space headroom floor."""
    global _BUDGET, _HEADROOM
    _BUDGET = int(budget_bytes) if budget_bytes is not None else None
    if headroom_bytes is not None:
        _HEADROOM = int(headroom_bytes)


def budget() -> int | None:
    """The configured shared cache/artifact byte budget (None when only
    the env var / built-in default applies)."""
    return _BUDGET


def register_evictor(name: str, fn, priority: int = 0) -> None:
    """Register a pressure evictor. Priority 0 = re-derivable caches
    (evicted first), 1 = rotated snapshots (never the newest). Re-using
    a name replaces the previous registration (per-directory stores
    re-register on construction)."""
    _EVICTORS[name] = (int(priority), fn)


def register_compactor(name: str, fn) -> None:
    """Register a compaction step (runs after every evictor)."""
    _COMPACTORS[name] = fn


def unregister(name: str) -> None:
    _EVICTORS.pop(name, None)
    _COMPACTORS.pop(name, None)


def free_bytes(path: str) -> int:
    """Free bytes on `path`'s filesystem, as the ladder should see them:
    zero while a chaos ENOSPC window (`disk.preflight` site) is open."""
    if chaos.disk_full("disk.preflight"):
        return 0
    probe = path if os.path.isdir(path) else (os.path.dirname(path) or ".")
    try:
        return int(shutil.disk_usage(probe).free)
    except OSError:
        # an unstattable target will fail at write time with a better
        # error than anything preflight could synthesize
        return 1 << 62


def _default_cache_evictor(need_bytes: int) -> int:
    """The always-present priority-0 rung: drop the shared warm+exec
    LRU pool (re-derivable by construction — a cold cache only costs
    recompute). Lazy import: checkpoint.py imports this module."""
    from ..sim.checkpoint import prune_warm_cache, warm_cache_root

    root = warm_cache_root()
    removed = prune_warm_cache(root, max_bytes=0)
    return removed  # entry count; caller rechecks real free space


def preflight(path: str, need_bytes: int, kind: str = "artifact") -> None:
    """Free-space gate called before a durable write of ~`need_bytes`
    to `path`. Returns normally when the write can proceed; otherwise
    runs the evict -> compact ladder and, if the disk is still full,
    raises `DiskPressureError` with a `retry_after_s` backpressure hint.

    Reentrant calls (ladder work writing its own records) pass straight
    through — the outer preflight already owns the ladder."""
    global _IN_LADDER
    if _IN_LADDER:
        return
    stats["preflights"] += 1
    need = int(need_bytes) + _HEADROOM
    if free_bytes(path) >= need:
        return
    stats["pressure_events"] += 1
    _IN_LADDER = True
    try:
        rungs = sorted(
            [(prio, name, fn) for name, (prio, fn) in _EVICTORS.items()]
            + [(0, "cache-lru", _default_cache_evictor)],
        )
        for _prio, name, fn in rungs:
            try:
                fn(need)
            except Exception:  # noqa: BLE001 — eviction is best-effort
                pass
            stats["evictions_run"] += 1
            if free_bytes(path) >= need:
                return
        for name in sorted(_COMPACTORS):
            try:
                _COMPACTORS[name]()
            except Exception:  # noqa: BLE001 — compaction is best-effort
                pass
            stats["compactions_run"] += 1
            if free_bytes(path) >= need:
                return
    finally:
        _IN_LADDER = False
    stats["rejections"] += 1
    raise DiskPressureError(
        f"disk pressure: {kind} write of ~{int(need_bytes)} bytes to "
        f"{path} cannot proceed ({free_bytes(path)} free after "
        "evict+compact ladder); retry after backpressure window",
        path=path,
        need_bytes=int(need_bytes),
        retry_after_s=2.0,
    )
