"""Shared retry backoff — decorrelated jitter (DESIGN.md §17).

One policy for every retry loop in the tree: supervisor chunk retries,
`ServeClient` RETRY_AFTER backpressure, and the pool worker's reconnect
path. The previous per-site bare exponential backoff (delay *= 2) has a
failure mode that only shows up at fleet scale: when one fault front
(coordinator restart, device hiccup) knocks N workers over at the same
instant, deterministic doubling keeps their retries phase-locked — every
attempt lands as a synchronized storm. Decorrelated jitter (the AWS
architecture-blog variant) breaks the phase lock:

    delay(0)   = base
    delay(n+1) = min(cap, uniform(base, delay(n) * 3))

The expected delay still grows geometrically (so a persistent outage
backs off hard), but two workers that failed together draw independent
sleeps immediately, and the spread widens with every attempt.
"""

from __future__ import annotations

import random


class DecorrelatedJitter:
    """Stateful backoff schedule: call `next_delay()` per failed attempt,
    `reset()` after a success. An explicit `rng` (any random.Random) makes
    the schedule reproducible for tests; by default each instance draws
    from its own independent stream seeded by the system RNG."""

    def __init__(self, base: float = 0.5, cap: float = 30.0, rng=None):
        if base <= 0 or cap < base:
            raise ValueError(
                f"backoff needs 0 < base <= cap, got base={base} cap={cap}"
            )
        self.base = float(base)
        self.cap = float(cap)
        self._rng = rng if rng is not None else random.Random()
        self._prev = 0.0

    def next_delay(self) -> float:
        """The next sleep in seconds: uniform over [base, 3*prev], capped.
        The first call returns `base` exactly (fail fast once before the
        randomized spread kicks in)."""
        if self._prev <= 0.0:
            self._prev = self.base
        else:
            self._prev = min(
                self.cap, self._rng.uniform(self.base, self._prev * 3.0)
            )
        return self._prev

    def reset(self) -> None:
        """Back to the initial state after a success."""
        self._prev = 0.0


def jittered(hint: float, spread: float = 0.5, rng=None) -> float:
    """Spread a server-supplied delay hint (RETRY_AFTER) uniformly over
    [hint*(1-spread), hint*(1+spread)] so N clients told to come back in
    the same number of seconds don't all come back in the same instant."""
    h = max(0.0, float(hint))
    if h == 0.0 or spread <= 0.0:
        return h
    r = rng if rng is not None else random
    return r.uniform(h * (1.0 - spread), h * (1.0 + spread))
