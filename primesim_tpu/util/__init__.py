"""Small shared host-side utilities (no JAX imports)."""

from .backoff import DecorrelatedJitter, jittered

__all__ = ["DecorrelatedJitter", "jittered"]
