"""Reference-schema XML config loader (SURVEY.md §2 #11).

The reference parses an XML simulation config with libxml2 (`XmlParser`
producing `XmlSim`/`XmlCore`/`XmlCache`/`XmlNetwork` structs: core count +
CPI, per-level cache geometry, mesh dims + hop latencies, DRAM latency,
sync quantum — SURVEY.md §5.6). PROVENANCE: the reference checkout was
never delivered (SURVEY.md §0), so the exact element names are
[RECALL]-grade; this loader therefore accepts the documented schema below
*and* common aliases, and fails loudly on anything it cannot map. Layout:

    <sim>
      <sys>
        <num_cores>64</num_cores>
        <cpi_nonmem>1</cpi_nonmem>
        <sync_quantum>1000</sync_quantum>
        <dram_access_time>100</dram_access_time>
        <network>
          <net_width>8</net_width>
          <net_height>8</net_height>
          <link_latency>1</link_latency>
          <router_latency>1</router_latency>
        </network>
        <cache level="1">          <!-- private L1 -->
          <size>32768</size> <num_ways>4</num_ways>
          <line_size>64</line_size> <access_time>2</access_time>
        </cache>
        <cache level="2" shared="true" num_banks="64">   <!-- shared LLC -->
          <size>262144</size> <num_ways>8</num_ways>
          <line_size>64</line_size> <access_time>10</access_time>
        </cache>
      </sys>
    </sim>

Accepted aliases: n_cores/num_cores, quantum/sync_quantum,
dram_latency/dram_access_time, x_dimension/net_width,
y_dimension/net_height, ways/num_ways, latency/access_time.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

from .machine import CacheConfig, CoreConfig, MachineConfig, NocConfig

_ALIASES = {
    "n_cores": ("num_cores", "n_cores"),
    "cpi": ("cpi_nonmem", "cpi"),
    "quantum": ("sync_quantum", "quantum"),
    "dram_lat": ("dram_access_time", "dram_latency", "dram_lat"),
    "mesh_x": ("net_width", "x_dimension", "mesh_x"),
    "mesh_y": ("net_height", "y_dimension", "mesh_y"),
    "link_lat": ("link_latency", "link_lat"),
    "router_lat": ("router_latency", "router_lat"),
    "size": ("size",),
    "ways": ("num_ways", "ways", "associativity"),
    "line": ("line_size", "line"),
    "latency": ("access_time", "latency"),
}


def _find_int(
    root: ET.Element, key: str, default: int | None = None, where: str = ""
) -> int:
    for tag in _ALIASES[key]:
        el = root.find(f".//{tag}")
        if el is not None and el.text and el.text.strip():
            return int(el.text.strip())
    if default is not None:
        return default
    ctx = f" in {where}" if where else ""
    raise ValueError(f"xml config: missing element {_ALIASES[key][0]!r}{ctx}")


def _cache_from(el: ET.Element, name: str) -> CacheConfig:
    return CacheConfig(
        size=_find_int(el, "size", where=name),
        ways=_find_int(el, "ways", where=name),
        line=_find_int(el, "line", where=name),
        latency=_find_int(el, "latency", where=name),
    )


def load_xml(path: str) -> MachineConfig:
    """Parse a reference-schema XML file into a MachineConfig."""
    root = ET.parse(path).getroot()

    caches = root.findall(".//cache")
    if not caches:
        raise ValueError("xml config: no <cache> elements")
    private = None
    shared = None
    n_banks = None
    for c in caches:
        is_shared = c.get("shared", "false").lower() in ("true", "1", "yes")
        level = int(c.get("level", "1"))
        if is_shared:
            if shared is not None:
                raise ValueError("xml config: multiple shared cache levels")
            shared = c
            nb = c.get("num_banks")
            n_banks = int(nb) if nb else None
        elif private is None or level < int(private.get("level", "1")):
            private = c  # the innermost private level maps to L1
    if private is None or shared is None:
        raise ValueError(
            "xml config: need one private and one shared (shared=\"true\") "
            "cache level"
        )

    n_cores = _find_int(root, "n_cores")
    noc = NocConfig(
        mesh_x=_find_int(root, "mesh_x", 8),
        mesh_y=_find_int(root, "mesh_y", 8),
        link_lat=_find_int(root, "link_lat", 1),
        router_lat=_find_int(root, "router_lat", 1),
    )
    return MachineConfig(
        n_cores=n_cores,
        core=CoreConfig(cpi=_find_int(root, "cpi", 1)),
        l1=_cache_from(private, "l1"),
        llc=_cache_from(shared, "llc"),
        n_banks=n_banks if n_banks is not None else min(n_cores, 64),
        noc=noc,
        dram_lat=_find_int(root, "dram_lat", 100),
        quantum=_find_int(root, "quantum", 1000),
    )
