"""Machine configuration for primesim_tpu.

TPU-native replacement for the reference's XML config layer (SURVEY.md §2 #11:
`XmlParser` producing `XmlSim`/`XmlCore`/`XmlCache`/`XmlNetwork` struct trees).
Typed dataclasses are the source of truth; `primesim_tpu.config.xml_compat`
loads reference-schema XML files into these for A/B parity runs.

All latencies are integer cycles. Geometry fields used in mask arithmetic
(bank count, cache sets, line size) must be powers of two; the core count
may be arbitrary (heterogeneous big.LITTLE mixes, odd device meshes).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Sequence


def _is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


class FaultConfigError(ValueError):
    """A fault schedule or fault knob is malformed (DESIGN.md §12).

    Mirrors trace.format.TraceError: keyword fields locate the offending
    entry so the CLI prints `fault schedule: core:9 at step 100: ...`
    instead of a bare traceback, and `.location()` feeds structured
    (JSON-line) error reporting.

    `site` names the injection target ("core:3", "link:17"), `step` the
    scheduled step, `field` the offending config/schedule field.
    """

    def __init__(
        self,
        message: str,
        *,
        site: str | None = None,
        step: int | None = None,
        field: str | None = None,
    ):
        self.site = site
        self.step = step
        self.field = field
        where = []
        if site is not None:
            where.append(str(site))
        if step is not None:
            where.append(f"step {step}")
        if field is not None:
            where.append(f"field {field!r}")
        prefix = f"fault schedule: {', '.join(where)}: " if where else "fault schedule: "
        super().__init__(prefix + message)

    def location(self) -> dict:
        """Non-None locator fields, for structured error lines."""
        out = {}
        for k in ("site", "step", "field"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


class ConfigError(ValueError):
    """A machine-zoo model selector is unknown or the combination is
    incompatible (DESIGN.md §25).

    Mirrors FaultConfigError / parallel.sharding.DeviceMeshError: the CLI
    catches it, exits 2 and prints ONE structured `{"error": ...}` JSON
    line, so `topology="taurus"` fails at config load with a typed
    message instead of a mid-compile shape error.

    `selector` names the offending config field ("noc_topology",
    "coherence", "prefetcher"), `value` its rejected value.
    """

    def __init__(
        self,
        message: str,
        *,
        selector: str | None = None,
        value=None,
    ):
        self.selector = selector
        self.value = value
        where = []
        if selector is not None:
            where.append(str(selector))
        if value is not None:
            where.append(f"value {value!r}")
        prefix = (
            f"machine config: {', '.join(where)}: " if where
            else "machine config: "
        )
        super().__init__(prefix + message)

    def location(self) -> dict:
        """Non-None locator fields, for structured error lines."""
        out = {}
        if self.selector is not None:
            out["selector"] = self.selector
        if self.value is not None:
            out["value"] = str(self.value)
        return out


#: Valid static model-selector values (the machine zoo, DESIGN.md §25).
NOC_TOPOLOGIES = ("mesh", "torus", "ring")
COHERENCE_PROTOCOLS = ("mesi", "moesi")
PREFETCHERS = ("none", "stride")


#: Fault event kinds (config/schedule encoding; see faults/schedule.py)
FAULT_CORE_FAILSTOP = 1  # a = core id: fail-stop at the scheduled step
FAULT_LINK_FAIL = 2  # a = directed link id: permanent link failure
FAULT_LINK_DEGRADE = 3  # a = link id, b = extra cycles per traversal


@dataclass(frozen=True)
class CacheConfig:
    """Geometry + latency of one cache level (private L1 or one LLC bank)."""

    size: int  # bytes (per core for L1, per bank for LLC)
    ways: int
    line: int  # line size, bytes
    latency: int  # hit/lookup latency, cycles

    @property
    def sets(self) -> int:
        s = self.size // (self.ways * self.line)
        return s

    def validate(self, name: str) -> None:
        if not _is_pow2(self.line):
            raise ValueError(f"{name}.line must be a power of two, got {self.line}")
        if self.size % (self.ways * self.line) != 0:
            raise ValueError(f"{name}.size not divisible by ways*line")
        if not _is_pow2(self.sets):
            raise ValueError(f"{name}: sets={self.sets} must be a power of two")
        if self.latency < 0:
            raise ValueError(f"{name}.latency must be >= 0")


@dataclass(frozen=True)
class CoreConfig:
    """In-order core timing model (SURVEY.md §2 #2: CoreManager).

    `cpi` is the cycles-per-instruction for non-memory instructions. A
    heterogeneous (big.LITTLE-style) machine supplies `cpi_per_core` (one
    entry per core) or the compact `cpi_pattern` (tiled across cores, e.g.
    (1, 1, 3, 3) for alternating big/LITTLE pairs); per-core overrides
    pattern overrides `cpi`.
    """

    cpi: int = 1
    cpi_per_core: tuple[int, ...] | None = None
    cpi_pattern: tuple[int, ...] | None = None
    # O3-style overlap model (0 = pure in-order). Fraction (in 1/256ths) of a
    # miss latency hidden by the out-of-order window; applied as
    # charged = lat - (lat * o3_overlap_256 >> 8), still integer-exact.
    o3_overlap_256: int = 0

    def cpi_vector(self, n_cores: int) -> tuple[int, ...]:
        if self.cpi_per_core is not None:
            if len(self.cpi_per_core) != n_cores:
                raise ValueError("cpi_per_core length != n_cores")
            return tuple(self.cpi_per_core)
        if self.cpi_pattern is not None:
            p = self.cpi_pattern
            return tuple(p[i % len(p)] for i in range(n_cores))
        return (self.cpi,) * n_cores

    def validate(self) -> None:
        if self.cpi < 1 or (
            self.cpi_per_core is not None and any(c < 1 for c in self.cpi_per_core)
        ):
            raise ValueError("core cpi values must be >= 1")
        if self.cpi_pattern is not None and (
            not self.cpi_pattern or any(c < 1 for c in self.cpi_pattern)
        ):
            raise ValueError("cpi_pattern must be non-empty with values >= 1")
        if not (0 <= self.o3_overlap_256 < 256):
            raise ValueError("o3_overlap_256 must be in [0, 256)")


@dataclass(frozen=True)
class NocConfig:
    """2-D mesh NoC (SURVEY.md §2 #6: Network, XY routing, hop-by-hop).

    `contention=True` enables load-dependent queueing, in one of two
    models (`contention_model`):

    - ``"tile"`` — router occupancy at the HOME tile: every uncore
      transaction served at a tile in the same step (memory winners +
      read-joins at their home bank, lock/unlock RMWs at the lock's home,
      barrier arrivals at the barrier's home) queues behind the others;
      each is charged `contention_lat * (n_at_tile - 1)` extra cycles.
    - ``"link"`` — hop-by-hop per-LINK occupancy: each transaction's XY
      request+reply paths (barrier arrivals: the one-way arrival path)
      claim every directed mesh link they traverse; the charge is
      `contention_lat * max over the path of (link_occupancy - 1)` — the
      bottleneck-link queue. This makes path-crossing traffic contend
      even when home banks differ (BASELINE rung 3 "NoC-congestion
      heavy").
    - ``"router"`` — hop-by-hop router with PER-LINK QUEUE STATE CARRIED
      ACROSS STEPS (SURVEY.md §2 #6's hop-by-hop `Network` router): every
      directed link keeps a next-free-cycle clock (`MachineState.
      link_free`). A transaction's packet walks its XY route hop by hop:
      at each link it waits for `link_free + rank*link_lat` (rank =
      number of same-step packets on that link injected earlier in the
      canonical (clock, core) order — FIFO serialization at `link_lat`
      per packet), then occupies the link for `link_lat` and pays
      `router_lat` at the next router; waits cascade into later hops.
      After the step, each link's clock advances to its last departure.
      Uncontended, the walk reduces exactly to the analytic
      `hops*link_lat + (hops+1)*router_lat`. Probe/invalidation side
      legs keep analytic latency (model scope: request/reply/barrier
      arrival paths route through the queues). `contention_lat` is
      unused by this model.

    All models are implemented identically in the golden and JAX engines
    and charged before the O3 overlap reduction.
    """

    mesh_x: int = 8
    mesh_y: int = 8
    link_lat: int = 1  # per-hop link traversal, cycles
    router_lat: int = 1  # per-router, cycles ((hops+1) routers on a path)
    contention: bool = False
    contention_model: str = "tile"  # "tile" | "link" | "router"
    contention_lat: int = 1  # queueing cycles per concurrent transaction
    # STATIC topology selector (DESIGN.md §25): "mesh" (XY dimension-
    # ordered), "torus" (wrap-around XY, shorter way per ring) or "ring"
    # (one ring per row bridged by a column-0 spine ring). Part of
    # `timing_normalized()` like contention_model — it changes the
    # compiled route builder, never a traced value — so it joins the
    # jit / exec-cache key. All topologies share the mesh link numbering
    # (tile*4 + dir), keeping n_links and every scatter shape invariant.
    topology: str = "mesh"

    @property
    def n_tiles(self) -> int:
        return self.mesh_x * self.mesh_y


@dataclass(frozen=True)
class MachineConfig:
    """Full simulated machine (SURVEY.md §2 #11 `XmlSim` equivalent)."""

    n_cores: int = 64
    core: CoreConfig = field(default_factory=CoreConfig)
    l1: CacheConfig = field(default_factory=lambda: CacheConfig(32 * 1024, 4, 64, 2))
    llc: CacheConfig = field(default_factory=lambda: CacheConfig(256 * 1024, 8, 64, 10))
    n_banks: int = 64
    noc: NocConfig = field(default_factory=NocConfig)
    dram_lat: int = 100
    # Memory-controller queueing (SURVEY.md §2 #7's "later: queueing
    # model per controller"): each LLC bank's co-located controller keeps
    # a next-free clock carried across steps; a miss whose request
    # arrives while the controller is busy waits for
    # `max(dram_free[bank], base) + rank*dram_service` (rank = earlier
    # same-step misses to the bank in (clock, core) order, base = the
    # bank's earliest nominal arrival this step — the same FIFO shape as
    # the router NoC model). `dram_service` is the controller occupancy
    # per access (0 -> dram_lat, a fully serialized controller). Waits
    # are charged before the O3 reduction and counted in
    # `dram_queue_cycles`; golden and engine are bit-exact.
    dram_queue: bool = False
    dram_service: int = 0
    # Route the dense sharer-expansion reductions through the Pallas TPU
    # kernel (primesim_tpu/ops/reductions.py) instead of the jnp path —
    # bit-identical results; full-map vectors only (the coarse/chunked
    # modes have their own reduction shapes). On non-TPU backends the
    # kernel runs interpreted, so tests exercise it everywhere.
    pallas_reduce: bool = False
    quantum: int = 1000  # relaxed-sync quantum, cycles (the fidelity/speed knob)
    # Local-run length: how many LOCAL events (INS batches, L1 hits) each
    # core may retire per step BEFORE the one arbitrated uncore event
    # (DESIGN.md §3 "local runs"). 0 = one event per core per step. This is
    # the analogue of the reference frontend never crossing a process
    # boundary for non-miss work (SURVEY.md §3.2): private hits shouldn't
    # cost a simulation step.
    local_run_len: int = 0
    # Synchronization modeling (DESIGN.md §3-sync; the reference intercepts
    # pthread mutex/barrier calls, SURVEY.md §2 #1). Mutex addresses hash
    # into `lock_slots` table entries (collisions = conservative false
    # contention); barrier ids must be dense ints < `barrier_slots`.
    lock_slots: int = 1024
    barrier_slots: int = 64
    # Sharer-reduction chunking (BASELINE rungs 4-5 memory bound): 0 =
    # dense [C, C] expansion of sharer bit-vectors for invalidation/
    # back-invalidation reductions (fastest at <= 1024 cores); K > 0 =
    # lax.scan over K-word blocks of the packed sharer words, bounding
    # per-step temporaries to [C, 32K] instead of [C, C] (4096+ cores).
    # Bit-exact either way. K must divide n_sharer_words.
    sharer_chunk_words: int = 0
    # COARSE SHARER VECTOR (Dir-G; SURVEY.md §2 #4, BASELINE rung 5): each
    # directory bit covers a GROUP of `sharer_group` consecutive cores,
    # dividing sharer storage by G — the full-map vector at 16384 cores x
    # 16.8M entries is 256 GiB, impossible on any chip; G=64 makes it
    # ~1 GiB. 1 = exact full-map. G > 1 is CONSERVATIVE, the classic
    # coarse-vector trade (Gupta et al.): invalidations broadcast to every
    # core of each flagged group (the requester is skipped as a message
    # but still bounds the serialization latency), a line is exclusive
    # (E-grantable) only when NO group bit is set, and read-join
    # coalescing is disabled (same-group joiners' bit updates would not
    # commute). Both engines implement the identical model; parity is
    # proven at small scale with G in {4, 32} (tests/test_coarse.py).
    sharer_group: int = 1
    # Step-body implementation (DESIGN.md §11): "xla" keeps the original
    # per-phase gather/scatter graph; "pallas" routes the step's dominant
    # serial segments through the VMEM-resident fused kernels in
    # primesim_tpu/kernels/ (probe_classify + commit, plus the sharer
    # reduction) to beat the per-kernel-overhead floor on TPU. Bit-exact
    # either way (tests/test_step_pallas.py proves golden/xla/pallas
    # three-way parity); a GEOMETRY selector, so it is part of the jit
    # key but timing knobs stay traced — fleet sweeps still compile once.
    # On non-TPU backends the kernels run in Pallas interpreter mode.
    step_impl: str = "xla"
    # ---- machine zoo selectors (DESIGN.md §25) --------------------------
    # STATIC coherence selector: "mesi" (the default pull-based protocol)
    # or "moesi" — adds the Owned state: a GETS to a modified line leaves
    # the dirty copy with its owner (no downgrade writeback) while other
    # sharers are recorded; O is DERIVED from the directory (owner == c
    # with other sharers), never stored in the L1 plane, so the state
    # encoding and every kernel layout are unchanged. Requires
    # sharer_group == 1 (a coarse group bit cannot distinguish the owner
    # from its own group's other cores).
    coherence: str = "mesi"
    # STATIC per-core prefetcher selector: "none" or "stride" (a stride-
    # detecting line prefetcher trained on each core's arbitrated uncore
    # stream; hits replace the DRAM latency of an LLC miss with the
    # traced `prefetch_lat`). The DEGREE and latency are TRACED knobs
    # (TimingKnobs.prefetch_degree / prefetch_lat) so a calibrate/sweep
    # fan over them never recompiles.
    prefetcher: str = "none"
    prefetch_degree: int = 4  # lines ahead a trained stream covers
    prefetch_lat: int = 0  # cycles an LLC miss costs on a prefetch hit
    # ---- fault injection (DESIGN.md §12) --------------------------------
    # `faults_enabled` is a STATIC model selector: when False (default)
    # the step function never touches the fault state and the compiled
    # graph is IDENTICAL to a build without the subsystem — the faults-off
    # bit-exactness + zero-overhead contract holds by construction.
    faults_enabled: bool = False
    # STATIC schedule capacity (array geometry, part of the jit key):
    # the scheduled events live in [max_fault_events]-sized traced arrays.
    max_fault_events: int = 0
    # STATIC policy selectors: what happens to a dead core's owned
    # (dirty-conservative) L1 lines — "writeback" keeps them in the LLC
    # (ownerless), "drop" invalidates the LLC entries too; whether an L1
    # detected-uncorrectable ECC error escalates to a core fail-stop.
    fault_dead_policy: str = "writeback"
    fault_due_failstop: bool = False
    # TRACED fault knobs (carried into state.FaultState by init_state and
    # blanked by timing_normalized, exactly like the timing knobs): the
    # PRNG seed, the scheduled events (step, kind, a, b) — kinds are the
    # FAULT_* constants above — and the per-site per-step bit-flip /
    # DUE-classification probabilities. A `sweep --vary fault_seed`
    # fan-out therefore NEVER recompiles.
    fault_seed: int = 0
    fault_events: tuple = ()
    fault_flip_l1: float = 0.0
    fault_flip_llc: float = 0.0
    fault_due_rate: float = 0.0

    def __post_init__(self):
        self.validate()

    def validate(self) -> None:
        if self.n_cores < 1:
            raise ValueError("n_cores must be >= 1")
        if not _is_pow2(self.n_banks):
            raise ValueError("n_banks must be a power of two")
        self.core.validate()
        self.l1.validate("l1")
        self.llc.validate("llc")
        if self.l1.line != self.llc.line:
            raise ValueError("l1 and llc line sizes must match")
        if self.quantum <= 0:
            raise ValueError("quantum must be positive")
        if self.dram_lat < 0:
            raise ValueError("dram_lat must be >= 0")
        if self.dram_service < 0:
            raise ValueError("dram_service must be >= 0")
        if self.noc.link_lat < 0 or self.noc.router_lat < 0:
            raise ValueError("NoC latencies must be >= 0")
        if self.noc.contention_lat < 0:
            raise ValueError("contention_lat must be >= 0")
        if self.noc.contention_model not in ("tile", "link", "router"):
            raise ValueError(
                "contention_model must be 'tile', 'link' or 'router'"
            )
        if self.noc.mesh_x < 1 or self.noc.mesh_y < 1:
            raise ValueError("mesh dims must be >= 1")
        if self.noc.topology not in NOC_TOPOLOGIES:
            raise ConfigError(
                f"unknown NoC topology (have: {', '.join(NOC_TOPOLOGIES)})",
                selector="noc_topology", value=self.noc.topology,
            )
        if self.coherence not in COHERENCE_PROTOCOLS:
            raise ConfigError(
                "unknown coherence protocol (have: "
                f"{', '.join(COHERENCE_PROTOCOLS)})",
                selector="coherence", value=self.coherence,
            )
        if self.coherence == "moesi" and self.sharer_group > 1:
            raise ConfigError(
                "moesi requires sharer_group == 1: the derived Owned "
                "state needs exact sharer identity, which a coarse "
                "group bit cannot provide",
                selector="coherence", value="moesi",
            )
        if self.prefetcher not in PREFETCHERS:
            raise ConfigError(
                f"unknown prefetcher (have: {', '.join(PREFETCHERS)})",
                selector="prefetcher", value=self.prefetcher,
            )
        if self.prefetch_degree < 1:
            raise ConfigError(
                "prefetch_degree must be >= 1",
                selector="prefetch_degree", value=self.prefetch_degree,
            )
        if self.prefetch_lat < 0:
            raise ConfigError(
                "prefetch_lat must be >= 0",
                selector="prefetch_lat", value=self.prefetch_lat,
            )
        if not (0 <= self.local_run_len <= 64):
            raise ValueError("local_run_len must be in [0, 64]")
        if not _is_pow2(self.lock_slots):
            raise ValueError("lock_slots must be a power of two")
        if not _is_pow2(self.barrier_slots):
            raise ValueError("barrier_slots must be a power of two")
        if not _is_pow2(self.sharer_group):
            raise ValueError("sharer_group must be a power of two >= 1")
        if self.pallas_reduce and (
            self.sharer_group > 1 or self.sharer_chunk_words
        ):
            raise ValueError(
                "pallas_reduce covers the dense full-map reduction only "
                "(sharer_group == 1, sharer_chunk_words == 0)"
            )
        if self.step_impl not in ("xla", "pallas"):
            raise ValueError("step_impl must be 'xla' or 'pallas'")
        if self.sharer_chunk_words < 0:
            raise ValueError("sharer_chunk_words must be >= 0")
        if self.sharer_chunk_words and (
            self.n_sharer_words % self.sharer_chunk_words
        ):
            raise ValueError(
                f"sharer_chunk_words={self.sharer_chunk_words} must divide "
                f"n_sharer_words={self.n_sharer_words}"
            )
        self._validate_faults()

    def _validate_faults(self) -> None:
        """Fault-injection knob validation (typed FaultConfigError)."""
        if self.fault_dead_policy not in ("writeback", "drop"):
            raise FaultConfigError(
                f"fault_dead_policy must be 'writeback' or 'drop', got "
                f"{self.fault_dead_policy!r}",
                field="fault_dead_policy",
            )
        if self.max_fault_events < 0:
            raise FaultConfigError(
                "max_fault_events must be >= 0", field="max_fault_events"
            )
        for name in ("fault_flip_l1", "fault_flip_llc", "fault_due_rate"):
            v = getattr(self, name)
            if not (0.0 <= v <= 1.0):
                raise FaultConfigError(
                    f"{name}={v} must be a probability in [0, 1]", field=name
                )
        if len(self.fault_events) > self.max_fault_events:
            raise FaultConfigError(
                f"{len(self.fault_events)} scheduled events exceed "
                f"max_fault_events={self.max_fault_events}",
                field="max_fault_events",
            )
        nl = self.n_tiles * 4  # directed links (noc.mesh.n_links)
        for ev in self.fault_events:
            if len(ev) != 4:
                raise FaultConfigError(
                    f"event {ev!r} must be (step, kind, a, b)",
                    field="fault_events",
                )
            estep, kind, a, b = (int(x) for x in ev)
            if estep < 0:
                raise FaultConfigError(
                    "scheduled step must be >= 0", step=estep,
                    field="fault_events",
                )
            if kind == FAULT_CORE_FAILSTOP:
                if not (0 <= a < self.n_cores):
                    raise FaultConfigError(
                        f"core id {a} out of range [0, {self.n_cores})",
                        site=f"core:{a}", step=estep, field="fault_events",
                    )
                if self.sharer_group > 1:
                    raise FaultConfigError(
                        "core fail-stop requires sharer_group == 1: a "
                        "coarse group bit covers live neighbors, so the "
                        "dead core's sharer bits cannot be scrubbed "
                        "without invalidating them too",
                        site=f"core:{a}", step=estep, field="sharer_group",
                    )
            elif kind in (FAULT_LINK_FAIL, FAULT_LINK_DEGRADE):
                if not (0 <= a < nl):
                    raise FaultConfigError(
                        f"link id {a} out of range [0, {nl})",
                        site=f"link:{a}", step=estep, field="fault_events",
                    )
                if self.noc.topology == "ring":
                    # a ring's only fallback is the LONG way around the
                    # affected ring (noc/ring.py detour_hops_table), which
                    # needs >= 3 positions to exist
                    if self.noc.mesh_x < 3 or self.noc.mesh_y < 3:
                        raise FaultConfigError(
                            "ring link faults need mesh_x >= 3 and "
                            "mesh_y >= 3 (the detour is the long way "
                            "around the affected ring)",
                            site=f"link:{a}", step=estep, field="noc",
                        )
                elif self.noc.mesh_x < 2 or self.noc.mesh_y < 2:
                    raise FaultConfigError(
                        "link faults need a >= 2x2 mesh (the X-Y fallback "
                        "detours around the failed hop through an "
                        "adjacent row/column)",
                        site=f"link:{a}", step=estep, field="noc",
                    )
                if kind == FAULT_LINK_DEGRADE and b < 0:
                    raise FaultConfigError(
                        "degrade extra latency must be >= 0",
                        site=f"link:{a}", step=estep, field="fault_events",
                    )
            else:
                raise FaultConfigError(
                    f"unknown fault kind {kind}", step=estep,
                    field="fault_events",
                )

    def timing_normalized(self) -> "MachineConfig":
        """This config with every TRACED timing knob (sim.state.TimingKnobs:
        quantum, cpi, cache/NoC/DRAM latencies) replaced by a fixed
        placeholder. Geometry and model selectors survive untouched, so two
        configs agree here iff they can share one compiled program — the
        fleet engine's static jit key (timing comes from the traced knobs
        carried in state, never from this config)."""
        return dataclasses.replace(
            self,
            quantum=1,
            core=dataclasses.replace(
                self.core, cpi=1, cpi_per_core=None, cpi_pattern=None
            ),
            l1=dataclasses.replace(self.l1, latency=1),
            llc=dataclasses.replace(self.llc, latency=1),
            noc=dataclasses.replace(
                self.noc, link_lat=1, router_lat=1, contention_lat=1
            ),
            dram_lat=1,
            dram_service=0,
            # traced prefetcher knobs blank too (they ride in
            # state.TimingKnobs); the `prefetcher` SELECTOR survives
            prefetch_degree=1,
            prefetch_lat=1,
            # traced fault knobs blank out too (seed/schedule/rates ride
            # in state.FaultState); the STATIC selectors (faults_enabled,
            # max_fault_events, policies) survive — they change the graph
            fault_seed=0,
            fault_events=(),
            fault_flip_l1=0.0,
            fault_flip_llc=0.0,
            fault_due_rate=0.0,
        )

    # Derived geometry used by both engines --------------------------------

    @property
    def line_bits(self) -> int:
        return self.l1.line.bit_length() - 1

    @property
    def n_sharer_groups(self) -> int:
        return (self.n_cores + self.sharer_group - 1) // self.sharer_group

    @property
    def n_sharer_words(self) -> int:
        return (self.n_sharer_groups + 31) // 32

    @property
    def n_tiles(self) -> int:
        return self.noc.n_tiles

    # Serialization --------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "MachineConfig":
        # keys starting with "_" are annotations ("_comment"), not fields
        d = {k: v for k, v in d.items() if not k.startswith("_")}
        if "core" in d and isinstance(d["core"], dict):
            c = dict(d["core"])
            if c.get("cpi_per_core") is not None:
                c["cpi_per_core"] = tuple(c["cpi_per_core"])
            if c.get("cpi_pattern") is not None:
                c["cpi_pattern"] = tuple(c["cpi_pattern"])
            d["core"] = CoreConfig(**c)
        if "l1" in d and isinstance(d["l1"], dict):
            d["l1"] = CacheConfig(**d["l1"])
        if "llc" in d and isinstance(d["llc"], dict):
            d["llc"] = CacheConfig(**d["llc"])
        if "noc" in d and isinstance(d["noc"], dict):
            d["noc"] = NocConfig(**d["noc"])
        if "fault_events" in d and d["fault_events"] is not None:
            d["fault_events"] = tuple(
                tuple(int(x) for x in ev) for ev in d["fault_events"]
            )
        return MachineConfig(**d)

    @staticmethod
    def from_json(s: str) -> "MachineConfig":
        return MachineConfig.from_dict(json.loads(s))


def small_test_config(n_cores: int = 4, **kw) -> MachineConfig:
    """Tiny machine for unit tests: 4 cores, 2x2 mesh, small caches."""
    defaults = dict(
        n_cores=n_cores,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=10),
        n_banks=1 << (min(4, n_cores).bit_length() - 1),  # pow2 <= min(4, n)
        noc=NocConfig(mesh_x=2, mesh_y=2, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=1000,
    )
    defaults.update(kw)
    return MachineConfig(**defaults)
