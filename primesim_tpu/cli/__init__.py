"""`primetpu` command-line interface (SURVEY.md §2 #15).

The reference is launched as a hand-composed mpirun MPMD line plus Pin
invocation (SURVEY.md §3.1); the TPU-native framework collapses that into
one CLI:

    primetpu run configs/rung1_64core_fft.json --synth fft_like --report r.txt
    primetpu run cfg.json --trace app.ptpu --engine jax
    primetpu sweep cfg.json --synth fft_like --vary llc_lat=10 --vary llc_lat=20
    primetpu synth lock_contention:n_critical=32 --cores 64 --out lc.ptpu
    primetpu info configs/rung3_1024core_o3.json

`run` simulates a trace (from a PTPU file or a named synthetic generator)
on a machine config, prints a one-line JSON summary (the bench.py format),
and optionally writes the reference-style text report. Synth specs are
`name[:key=int,...]` over primesim_tpu.trace.synth.GENERATORS.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _parse_synth(spec: str, n_cores: int, fold: bool):
    from ..trace import synth
    from ..trace.format import fold_ins

    name, _, args = spec.partition(":")
    if name not in synth.GENERATORS:
        raise SystemExit(
            f"unknown generator {name!r}; have: {', '.join(sorted(synth.GENERATORS))}"
        )
    kw = {}
    if args:
        for pair in args.split(","):
            k, eq, v = pair.partition("=")
            if not eq or not k:
                raise SystemExit(f"bad synth arg {pair!r} (want key=value)")
            try:
                kw[k] = int(v)
            except ValueError:
                raise SystemExit(
                    f"bad synth arg {pair!r}: value must be an integer"
                ) from None
    try:
        tr = synth.GENERATORS[name](n_cores, **kw)
    except TypeError as e:
        raise SystemExit(f"synth {name!r}: {e}") from None
    return fold_ins(tr) if fold else tr


def _load_trace(ns, n_cores: int, line_bits: int = 6):
    from ..trace.format import Trace, fold_ins, multiplex

    if ns.trace:
        if len(ns.trace) > 1 and getattr(ns, "mmap", False):
            raise SystemExit(
                "--mmap is incompatible with multiple --trace flags: "
                "multiplexing materializes the combined trace in RAM"
            )
        trs = [
            Trace.load(p, mmap=getattr(ns, "mmap", False)) for p in ns.trace
        ]
        # several --trace flags = the reference's MULTIPROGRAMMED mode:
        # each program gets a disjoint address window and sync objects,
        # all sharing this machine's uncore
        tr = (
            trs[0]
            if len(trs) == 1
            else multiplex(trs, line_bits=line_bits)
        )
        return fold_ins(tr) if ns.fold else tr
    if ns.synth:
        return _parse_synth(ns.synth, n_cores, ns.fold)
    raise SystemExit("run: need --trace FILE or --synth SPEC")


def _load_config(path: str):
    if path.endswith(".xml"):
        from ..config.xml_compat import load_xml

        return load_xml(path)
    from ..config.machine import MachineConfig

    with open(path) as f:
        return MachineConfig.from_json(f.read())


def _emit_summary(
    ns, cfg, engine_name, counters, cycles, wall, extra=None,
    resilience=None, timeline=None,
):
    """Shared one-line JSON summary + optional text report (the single
    emission contract for every engine path)."""
    from ..stats.report import write_report

    tot_ins = int(counters["instructions"].sum())
    detail = {
        "engine": engine_name,
        "step_impl": cfg.step_impl if engine_name != "golden" else None,
        "n_cores": cfg.n_cores,
        "instructions": tot_ins,
        "max_core_cycles": int(max(cycles)),
        "wall_s": round(wall, 3),
        "noc_msgs": int(counters["noc_msgs"].sum()),
    }
    if extra:
        detail.update(extra)
    if timeline:
        detail["timeline"] = {
            "chunks": timeline["chunks"],
            "peak_chunk_mips": round(timeline["peak_chunk_mips"], 3),
            "mean_chunk_mips": round(timeline["mean_chunk_mips"], 3),
            "slowest_chunk_seq": timeline["slowest_chunk_seq"],
        }
    print(
        json.dumps(
            {
                "metric": "simulated_MIPS",
                "value": round(tot_ins / wall / 1e6, 3),
                "unit": "MIPS",
                "detail": detail,
            }
        )
    )
    if ns.report:
        write_report(
            ns.report, cfg, counters, cycles, wall_s=wall,
            per_core_limit=ns.per_core_limit,
            resilience=resilience, timeline=timeline,
        )
        print(f"report written to {ns.report}", file=sys.stderr)


def _supervised(ns) -> bool:
    """Any resilience flag engages the supervised (chunk-committed) path."""
    return bool(
        getattr(ns, "resume", False)
        or getattr(ns, "checkpoint_dir", None)
        or getattr(ns, "checkpoint_every", 0)
        or getattr(ns, "checkpoint_wall", 0.0)
        or getattr(ns, "guard", "off") != "off"
    )


def _check_supervision_flags(ns) -> None:
    if (
        ns.resume or ns.checkpoint_every or ns.checkpoint_wall
    ) and not ns.checkpoint_dir:
        raise SystemExit(
            "--resume/--checkpoint-every/--checkpoint-wall require "
            "--checkpoint-dir DIR (where snapshots live)"
        )


def _build_supervisor(ns, eng, obs=None):
    from ..sim.supervisor import RunSupervisor

    return RunSupervisor(
        eng,
        snapshot_dir=ns.checkpoint_dir,
        keep_snapshots=ns.keep_snapshots,
        checkpoint_every_chunks=ns.checkpoint_every,
        checkpoint_every_s=ns.checkpoint_wall,
        guard=ns.guard,
        max_retries=ns.max_retries,
        obs=obs,
    )


def _emit_preempted(e, sup) -> int:
    """Preemption is a clean outcome, not a crash: report where the run
    stopped and exit 75 (EX_TEMPFAIL — rerun with --resume)."""
    print(f"preempted: {e}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "preempted",
                "value": None,
                "unit": None,
                "detail": {
                    "checkpoint": e.checkpoint,
                    "signal": e.signum,
                    **sup.summary(),
                },
            }
        )
    )
    return 75


def _run_supervised(ns, cfg, eng, rec=None) -> int:
    """Supervised `run` path: chunk-committed execution under a
    RunSupervisor (auto-checkpoint, preemption, retry, guard)."""
    from ..sim.supervisor import Preempted

    if rec is not None:
        rec.attach(eng)
    sup = _build_supervisor(ns, eng, obs=rec)
    if ns.resume:
        sup.resume()
    t0 = time.perf_counter()
    try:
        sup.run(max_steps=ns.max_steps)  # None -> engine-appropriate budget
    except Preempted as e:
        _finalize_obs(rec)  # the flight recorder survives preemption
        return _emit_preempted(e, sup)
    wall = time.perf_counter() - t0
    extra = sup.summary()
    if getattr(eng, "attest", None) is not None:
        extra["attest"] = eng.attest.payload()
    _emit_summary(
        ns, cfg, ns.engine, eng.counters, eng.cycles, wall,
        extra=extra, resilience=sup.log_lines(),
        timeline=rec.timeline_summary() if rec is not None else None,
    )
    _finalize_obs(rec)
    return 0


def _apply_step_impl(ns, cfg):
    if getattr(ns, "step_impl", None) and ns.step_impl != cfg.step_impl:
        import dataclasses

        cfg = dataclasses.replace(cfg, step_impl=ns.step_impl)
    return cfg


def _apply_faults(ns, cfg):
    """Apply --fault-schedule/--fault-seed (DESIGN.md §12) to the config.

    The schedule sets the STATIC fault geometry (faults_enabled,
    max_fault_events, policies) — part of the jit key; the seed is a
    TRACED value, so `sweep --vary fault_seed=...` reuses one compiled
    program across the whole chaos sweep."""
    schedule = getattr(ns, "fault_schedule", None)
    seed = getattr(ns, "fault_seed", None)
    if schedule:
        from ..faults.schedule import load_schedule

        cfg = load_schedule(schedule).apply(cfg, seed=seed or 0)
    elif seed is not None:
        if not cfg.faults_enabled:
            raise SystemExit(
                "--fault-seed without --fault-schedule needs a config with "
                "faults_enabled (the seed only feeds an armed fault model)"
            )
        import dataclasses

        cfg = dataclasses.replace(cfg, fault_seed=seed)
    return cfg


def _build_mesh(ns, cfg):
    """--devices N -> a validated tile mesh (or None). Multi-chip: shard
    cores/L1s/events by core and the LLC/directory by bank over the first
    N visible devices; virtual CPU meshes work too
    (XLA_FLAGS=--xla_force_host_platform_device_count=N
    JAX_PLATFORMS=cpu). A bad N (doesn't divide the core/bank axes, or
    more devices than visible) raises the typed DeviceMeshError -> exit 2
    with a structured {"error": ...} line."""
    if not getattr(ns, "devices", 0):
        return None
    from ..parallel.sharding import tile_mesh, validate_devices

    validate_devices(cfg, ns.devices)
    mesh = tile_mesh(ns.devices)
    print(
        f"mesh: {ns.devices} devices "
        f"({mesh.devices.flat[0].platform})",
        file=sys.stderr,
    )
    return mesh


def _run_pipelined_cli(ns, cfg, tr, mesh, rec) -> int:
    """`run --stream-window W --ingest-workers K`: the pipelined rung-5
    path (DESIGN.md §22). Pool ingest workers materialize trace segments
    ahead of a supervised PipelineStreamEngine in THIS process; the
    supervisor contract (checkpoints/resume/guard/preemption) is the
    stream engine's, unchanged."""
    import os

    from ..ingest.pipeline import run_pipelined
    from ..sim.supervisor import Preempted

    traces = ns.trace or []
    if len(traces) + (1 if ns.synth else 0) != 1:
        raise SystemExit(
            "--ingest-workers needs exactly one --trace file or one "
            "--synth spec (workers re-materialize the source from its "
            "portable spec)"
        )
    if traces and ns.fold:
        raise SystemExit(
            "--ingest-workers does not compose with --fold for trace "
            "files yet (ingest workers re-read the raw file)"
        )
    trace_path = os.path.abspath(traces[0]) if traces else None
    sup_kwargs = dict(
        snapshot_dir=ns.checkpoint_dir,
        keep_snapshots=ns.keep_snapshots,
        checkpoint_every_chunks=ns.checkpoint_every,
        checkpoint_every_s=ns.checkpoint_wall,
        guard=ns.guard,
        max_retries=ns.max_retries,
        obs=rec,
    )
    t0 = time.perf_counter()
    try:
        eng, sup, ingest = run_pipelined(
            cfg, tr,
            trace_path=trace_path,
            synth_spec=ns.synth if not traces else None,
            window_events=ns.stream_window,
            seg_events=ns.seg_events or None,
            ingest_workers=ns.ingest_workers,
            pool_dir=ns.pool_dir,
            mesh=mesh,
            supervisor_kwargs=sup_kwargs,
            max_steps=ns.max_steps,
            resume=bool(ns.resume),
            obs=rec,
            log=lambda m: print(f"run: {m}", file=sys.stderr),
        )
    except Preempted as e:
        _finalize_obs(rec)
        return _emit_preempted(e, e.supervisor)
    wall = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": "ingest_pipeline",
                "value": ingest["segments"],
                "unit": "segments",
                "detail": ingest,
            }
        )
    )
    for line in sup.log_lines():
        print(f"supervisor: {line}", file=sys.stderr)
    _emit_summary(
        ns, cfg, ns.engine, eng.counters, eng.cycles, wall,
        extra=sup.summary(),
        timeline=rec.timeline_summary() if rec is not None else None,
    )
    _finalize_obs(rec)
    return 0


def cmd_run(ns) -> int:
    t_start = time.perf_counter()  # time_to_first_step epoch
    cache = _activate_exec_cache(ns)
    overlap = getattr(ns, "overlap", "off") == "on"
    if ns.engine == "golden" and (cache is not None or overlap):
        raise SystemExit(
            "--exec-cache/--overlap require --engine jax (the golden "
            "oracle has no compiled program or device loop)"
        )
    cfg = _apply_faults(ns, _apply_step_impl(ns, _load_config(ns.config)))
    if cfg.faults_enabled and ns.engine == "golden":
        raise SystemExit(
            "fault injection requires --engine jax (the golden oracle "
            "models the fault-free machine)"
        )
    if cfg.faults_enabled and ns.stream_window:
        raise SystemExit(
            "fault injection does not compose with --stream-window yet "
            "(window rebasing assumes the fault-free retirement order)"
        )
    tr = _load_trace(ns, cfg.n_cores, line_bits=cfg.line_bits)
    if tr.n_cores != cfg.n_cores:
        raise SystemExit(
            f"trace has {tr.n_cores} cores but config has {cfg.n_cores}"
        )
    _check_supervision_flags(ns)
    supervised = _supervised(ns)
    if supervised and (ns.xprof or ns.debug_invariants):
        raise SystemExit(
            "--xprof/--debug-invariants do not compose with the supervised "
            "path (--guard runs the same invariants post-chunk)"
        )
    rec = _build_recorder(ns)
    if rec is not None and ns.engine == "golden":
        raise SystemExit(
            "--obs requires --engine jax (the golden oracle has no "
            "chunk loop to instrument)"
        )
    if rec is not None and ns.xprof:
        raise SystemExit(
            "--obs does not compose with --xprof (pick the flight "
            "recorder OR the XLA profiler for a given run)"
        )
    attest_on = getattr(ns, "attest", "off") == "chain"
    if attest_on and ns.engine == "golden":
        raise SystemExit(
            "--attest requires --engine jax (the chain fingerprints "
            "committed chunk state; the golden oracle has no chunk loop)"
        )

    if ns.engine == "golden":
        if (
            ns.xprof or ns.debug_invariants or ns.stream_window
            or ns.devices or supervised
        ):
            raise SystemExit(
                "--xprof/--debug-invariants/--stream-window/--devices and "
                "the checkpoint/resume/guard flags require --engine jax "
                "(the golden oracle has no device loop)"
            )
        from ..golden.sim import GoldenSim

        t0 = time.perf_counter()
        sim = GoldenSim(cfg, tr)
        sim.run(max_steps=ns.max_steps or 10_000_000)
        wall = time.perf_counter() - t0
        cycles, counters = sim.cycles, sim.counters
    elif ns.stream_window:
        # bounded-memory windowed ingest: device memory O(C * window),
        # host O(1) with --mmap; bit-exact vs the preloaded engine
        from ..ingest.stream import StreamEngine

        if ns.xprof or ns.debug_invariants:
            raise SystemExit(
                "--xprof/--debug-invariants are not supported with "
                "--stream-window yet"
            )
        mesh = _build_mesh(ns, cfg)
        if ns.ingest_workers:
            # rung-5 pipelined path (DESIGN.md §22): pool workers ingest
            # trace segments ahead of a supervised stream engine
            return _run_pipelined_cli(ns, cfg, tr, mesh, rec)
        eng = StreamEngine(cfg, tr, window_events=ns.stream_window,
                           mesh=mesh)
        if attest_on:
            # window-scoped chain: the stream engine's natural chunk is
            # the window, so the cadence field is the window size
            from ..attest import SoloAttest

            eng.attest = SoloAttest(ns.stream_window)
        if overlap:
            print(
                "overlap: the stream engine's next window is produced by "
                "the host fill/absorb cycle itself — nothing to "
                "speculate; running without overlap",
                file=sys.stderr,
            )
        # warm the jit cache at the run's window shapes so the reported
        # MIPS measures simulation, not compilation — same protocol as the
        # preloaded path above
        eng.warmup()
        _emit_ttfs_line(cache, t_start)
        if supervised:
            rc = _run_supervised(ns, cfg, eng, rec=rec)
            _emit_exec_cache_line(cache)
            return rc
        if rec is not None:
            rec.attach(eng)  # streaming always windows; no path change
        t0 = time.perf_counter()
        eng.run(max_steps=ns.max_steps)  # None -> event-count-derived
        wall = time.perf_counter() - t0
        cycles, counters = eng.cycles, eng.counters
    else:
        import numpy as np

        import jax
        import jax.numpy as jnp

        from ..sim.engine import Engine, run_chunk, run_loop

        mesh = _build_mesh(ns, cfg)

        # warm the jit cache at the measured shapes (one chunk) so the
        # reported MIPS measures simulation, not compilation — the same
        # protocol as bench.py; comparable numbers matter more than the
        # one-off compile cost shown to an interactive user. The debug
        # path dispatches run_chunk, not the fused run_loop — warm the
        # function the run will actually use.
        warm = Engine(cfg, tr, chunk_steps=ns.chunk_steps, mesh=mesh)
        from ..sim import exec_cache

        if ns.debug_invariants or supervised or rec is not None or attest_on:
            # the chunked paths (debug + supervised run_steps) dispatch
            # run_chunk, not the fused run_loop — warm what will run
            # (routed through the exec cache so a warm process pays
            # deserialization here instead of XLA compile)
            out = exec_cache.call(
                run_chunk, "engine.run_chunk",
                (cfg, ns.chunk_steps), (warm.events, warm.state),
                {"has_sync": warm.has_sync},
            )
            np.asarray(out.cycles)  # block until compiled + run
        else:
            out = exec_cache.call(
                run_loop, "engine.run_loop",
                (cfg, ns.chunk_steps),
                (warm.events, warm.state, jnp.asarray(1, jnp.int32)),
                {"has_sync": warm.has_sync},
            )
            np.asarray(out[0].cycles)
        _emit_ttfs_line(cache, t_start)
        eng = Engine(cfg, tr, chunk_steps=ns.chunk_steps, mesh=mesh)
        eng.overlap = overlap
        if attest_on:
            from ..attest import SoloAttest

            eng.attest = SoloAttest(ns.chunk_steps)
        eng.block_until_ready()  # don't bill async uploads to simulation
        if supervised:
            rc = _run_supervised(ns, cfg, eng, rec=rec)
            _emit_exec_cache_line(cache)
            return rc
        if rec is not None:
            rec.attach(eng)

        def _go():
            if ns.debug_invariants or rec is not None or attest_on:
                # chunked dispatch: host visibility at every chunk is
                # what the telemetry (and the invariant checks) need
                eng.run_chunked(
                    max_steps=ns.max_steps or 10_000_000,
                    debug_invariants=ns.debug_invariants,
                )
            else:
                eng.run(max_steps=ns.max_steps or 10_000_000)

        t0 = time.perf_counter()
        if ns.xprof:
            with jax.profiler.trace(ns.xprof):
                _go()
            print(f"profiler trace written to {ns.xprof}", file=sys.stderr)
        else:
            _go()
        wall = time.perf_counter() - t0
        cycles, counters = eng.cycles, eng.counters

    _emit_summary(
        ns, cfg, ns.engine, counters, cycles, wall,
        extra={"attest": eng.attest.payload()} if attest_on else None,
        timeline=rec.timeline_summary() if rec is not None else None,
    )
    _emit_exec_cache_line(cache)
    _finalize_obs(rec)
    return 0


def cmd_capture(ns) -> int:
    """Execution-driven simulation of a real binary (SURVEY.md §2 #9):
    run the target under the LD_PRELOAD capture shim and either simulate
    ONLINE while it executes (default, shared-memory ring) or write a
    PTPU trace for later replay (--out)."""
    cfg = _load_config(ns.config)
    if ns.out:
        if ns.report:
            raise SystemExit(
                "--report needs a simulation: drop --out for online mode, "
                "or replay the trace with `primetpu run --trace`"
            )
        from ..ingest.capture import capture_run

        try:
            tr = capture_run(ns.command, line=cfg.l1.line)
        except RuntimeError as e:
            print(f"capture failed: {e}", file=sys.stderr)
            return 1
        tr.save(ns.out)
        print(
            f"wrote {ns.out}: {tr.n_cores} cores x {tr.max_len} events",
            file=sys.stderr,
        )
        return 0

    from ..ingest.capture import capture_online
    from ..ingest.ring import OnlineEngine

    proc, src = capture_online(
        ns.command, n_cores=cfg.n_cores, line=cfg.l1.line,
        retain_history=False,
    )
    try:
        eng = OnlineEngine(cfg, src, window_events=ns.window)
        # warm the jit cache outside the timed region — the shared
        # measurement protocol (every MIPS this CLI prints excludes
        # one-off compilation)
        eng.warmup()
        t0 = time.perf_counter()
        eng.run()
        wall = time.perf_counter() - t0
        rc = proc.wait(timeout=30)
        if rc != 0:
            print(f"target exited {rc}", file=sys.stderr)
        if src.dropped():
            print(
                f"WARNING: {src.dropped()} events dropped on full rings",
                file=sys.stderr,
            )
        _emit_summary(
            ns, cfg, "online", eng.counters, eng.cycles, wall,
            extra={"events": int(src.total.sum()), "target_rc": rc},
        )
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
        src.close()


class VarySpecError(ValueError):
    """A malformed --vary spec (bad shape, unknown key, non-integer
    value). Typed like TraceError/FaultConfigError so `main` exits 2
    with the structured {"error": ...} JSON instead of a bare usage
    message — sweep specs come from scripts at least as often as from
    hands, and scripts parse one error grammar everywhere."""

    def __init__(self, msg: str, pair: str | None = None):
        super().__init__(msg)
        self.pair = pair

    def location(self) -> dict:
        return {"pair": self.pair} if self.pair is not None else {}


def _parse_vary(spec: str) -> dict:
    """Parse one --vary spec 'k=v[,k=v...]' into a timing-override dict
    (keys validated against sim.fleet.KNOB_KEYS here AND by the
    FleetEngine — here so the error names the offending pair)."""
    from ..sim.fleet import KNOB_KEYS

    ov = {}
    for pair in spec.split(","):
        k, eq, v = pair.partition("=")
        if not eq or not k:
            raise VarySpecError(
                f"bad --vary arg {pair!r} (want key=value; valid keys: "
                f"{', '.join(KNOB_KEYS)})",
                pair=pair,
            )
        if k not in KNOB_KEYS:
            raise VarySpecError(
                f"bad --vary arg {pair!r}: unknown key {k!r} (valid keys: "
                f"{', '.join(KNOB_KEYS)})",
                pair=pair,
            )
        try:
            ov[k] = int(v)
        except ValueError:
            raise VarySpecError(
                f"bad --vary arg {pair!r}: value must be an integer "
                f"(valid keys: {', '.join(KNOB_KEYS)})",
                pair=pair,
            ) from None
    return ov


def cmd_sweep(ns) -> int:
    """Fan a config + timing overrides and/or traces into ONE fleet run
    (sim.fleet.FleetEngine): every element shares the compiled program —
    one compilation per geometry — and the batch retires one event per
    core per element per step. Emits one JSON summary line per element
    (ordered by fleet index) plus a fleet_aggregate_MIPS line.

    Fault isolation is the default: an element whose trace file is
    unreadable/malformed or whose overrides are invalid is QUARANTINED
    (reported in its own JSON line, with the TraceError's core/offset
    when available) while the rest of the batch runs; `--strict` makes
    any bad element fatal instead."""
    import os

    if ns.fork_prefix not in ("auto", "off"):
        try:
            int(ns.fork_prefix)
        except ValueError:
            raise SystemExit(
                f"sweep: --fork-prefix must be auto, off, or an integer "
                f"step cap (got {ns.fork_prefix!r})"
            ) from None
    t_start = time.perf_counter()
    cache = _activate_exec_cache(ns)
    overlap = getattr(ns, "overlap", "off") == "on"
    cfg = _apply_faults(ns, _apply_step_impl(ns, _load_config(ns.config)))
    _check_supervision_flags(ns)
    if ns.workers:
        # elastic pool path (DESIGN.md §17): coordinator in-process, N
        # worker subprocesses leasing units over the serve protocol
        from ..pool.campaign import run_pooled_sweep

        return run_pooled_sweep(ns, cfg)
    if ns.report:
        raise SystemExit(
            "sweep: --report is the pooled campaign report (--workers); "
            "use --report-dir for per-element reports"
        )
    from ..trace.format import Trace, TraceError, fold_ins

    # per-element SOURCES: callables for file loads (so an unreadable
    # file quarantines one element, not the sweep), eager traces for
    # synth specs (a bad spec is operator error — SystemExit above)
    def _loader(path):
        def load():
            t = Trace.load(path)
            return fold_ins(t) if ns.fold else t

        return load

    sources: list = [_loader(p) for p in (ns.trace or [])]
    for spec in ns.synth or []:
        sources.append(_parse_synth(spec, cfg.n_cores, ns.fold))
    if not sources:
        raise SystemExit("sweep: need --trace FILE and/or --synth SPEC")
    ovs = [_parse_vary(s) for s in (ns.vary or [])]
    A, V = len(sources), len(ovs)
    # fan rule: equal lengths pair up; a single trace (or single --vary)
    # replicates across the other axis; anything else is ambiguous
    if V == 0:
        ovs = [{}] * A
    elif A == 1 and V > 1:
        sources = sources * V
    elif V == 1 and A > 1:
        ovs = ovs * A
    elif A != V:
        raise SystemExit(
            f"sweep: {A} traces vs {V} --vary sets — lengths must match, "
            "or one side must be a single entry to replicate"
        )

    import numpy as np

    import jax.numpy as jnp

    from ..sim.fleet import FleetEngine, fleet_run_chunk, fleet_run_loop
    from ..sim.supervisor import Preempted, build_fleet_isolated

    supervised = _supervised(ns)
    rec = _build_recorder(ns)
    mesh = _build_mesh(ns, cfg)
    if ns.strict:
        traces = [s() if callable(s) else s for s in sources]
        fleet = FleetEngine(cfg, traces, ovs, chunk_steps=ns.chunk_steps,
                            mesh=mesh)
        quarantined: list = []
    else:
        fleet, quarantined = build_fleet_isolated(
            cfg, sources, ovs, chunk_steps=ns.chunk_steps, mesh=mesh
        )
    from ..serve.protocol import error_obj

    for i, err in quarantined:
        detail = {
            "engine": "fleet",
            "fleet_index": i,
            "status": "quarantined",
            "overrides": ovs[i],
            **error_obj(err),  # structured {"error": {type, location, detail}}
        }
        if isinstance(err, TraceError):
            detail.update(err.location())
        print(
            json.dumps(
                {
                    "metric": "quarantined",
                    "value": None,
                    "unit": None,
                    "detail": detail,
                }
            )
        )
    if fleet is None:
        print("sweep: every element was quarantined", file=sys.stderr)
        return 1

    # identical-element dedup: two elements with equal (trace, effective
    # config) would simulate the same run twice — keep the first, fan its
    # report out to the twins afterwards (caller indices are preserved
    # via element_ids, same as quarantine)
    from ..sim.prefix import dedup_plan, execute_prefix_plan, plan_prefix

    dup_of_caller: dict[int, int] = {}
    if fleet.n_elements > 1:
        keep, dup_of = dedup_plan(fleet.elem_cfgs, fleet.traces)
        if dup_of:
            ids = fleet.element_ids
            dup_of_caller = {ids[j]: ids[k] for j, k in dup_of.items()}
            print(
                "sweep: WARNING: deduplicated "
                f"{len(dup_of)} identical element(s) — "
                + ", ".join(
                    f"{ids[j]} duplicates {ids[k]}"
                    for j, k in sorted(dup_of.items())
                )
                + " (simulated once, reports fanned out)",
                file=sys.stderr,
            )
            kept_ids = [ids[j] for j in keep]
            fleet = FleetEngine(
                cfg,
                [fleet.traces[j] for j in keep],
                [fleet.element_overrides[j] for j in keep],
                chunk_steps=ns.chunk_steps,
                mesh=mesh,
            )
            fleet.element_ids = kept_ids

    # warm the jit cache at the fleet's shapes (one chunk) — the shared
    # protocol: reported MIPS measures simulation, not compilation. The
    # supervised path dispatches fleet_run_chunk (chunk-committed), the
    # fused path fleet_run_loop — warm what will run.
    warm = FleetEngine(
        cfg, fleet.traces, fleet.element_overrides,
        chunk_steps=ns.chunk_steps, mesh=mesh,
    )
    from ..sim import exec_cache

    if supervised or rec is not None:
        out_st = exec_cache.call(
            fleet_run_chunk, "fleet.run_chunk",
            (warm.geom_cfg, warm.chunk_steps), (warm.events, warm.state),
            {"has_sync": warm.has_sync},
        )
        np.asarray(out_st.cycles)
    else:
        out = exec_cache.call(
            fleet_run_loop, "fleet.run_loop",
            (warm.geom_cfg, warm.chunk_steps),
            (warm.events, warm.state, jnp.asarray(1, jnp.int32)),
            {"has_sync": warm.has_sync},
        )
        np.asarray(out[0].cycles)
    _emit_ttfs_line(cache, t_start)
    fleet.overlap = overlap
    fleet.block_until_ready()
    if rec is not None:
        rec.attach(fleet)

    def _fork_now() -> dict:
        # run (or warm-load) each prefix-sharing class's shared prefix
        # and fork it into the slots; the metric line is the scriptable
        # record of what was skipped (CI parses cache_hits from it)
        groups = plan_prefix(
            fleet.elem_cfgs,
            fleet.traces,
            mode=ns.fork_prefix,
            chunk_steps=ns.chunk_steps,
            cap=ns.max_steps or 10_000_000,
        )
        st = execute_prefix_plan(
            fleet, groups, warm_cache=ns.warm_cache == "on", obs=rec
        )
        st["mode"] = ns.fork_prefix
        st["warm_cache"] = ns.warm_cache
        if dup_of_caller:
            st["deduped"] = sorted(dup_of_caller)
        print(
            json.dumps(
                {
                    "metric": "prefix_fork",
                    "value": st["forked_elements"],
                    "unit": "elements",
                    "detail": st,
                }
            )
        )
        return st

    stalled: list[int] = []
    if supervised:
        sup = _build_supervisor(ns, fleet, obs=rec)
        resumed = sup.resume() if ns.resume else None
        if resumed is None and ns.fork_prefix != "off":
            # a restored snapshot is already past the prefix (and carries
            # its fork provenance); fork only on a fresh start
            _fork_now()
        t0 = time.perf_counter()
        try:
            sup.run(max_steps=ns.max_steps or 10_000_000)
        except Preempted as e:
            _finalize_obs(rec)
            return _emit_preempted(e, sup)
        wall = time.perf_counter() - t0
        stalled = list(sup.stalled_elements)
        for line in sup.log_lines():
            print(f"supervisor: {line}", file=sys.stderr)
    else:
        if ns.fork_prefix != "off":
            _fork_now()
        t0 = time.perf_counter()
        try:
            if rec is not None:
                # chunked dispatch so every chunk lands in the metric
                # ring; same stall isolation as the fused path
                fleet.run_steps(ns.max_steps or 10_000_000)
                if not fleet.done():
                    bad = np.flatnonzero(~fleet.done_mask()).tolist()
                    raise RuntimeError(
                        f"fleet: max_steps exceeded on element(s) {bad} "
                        "(deadlock?)"
                    )
            else:
                fleet.run(max_steps=ns.max_steps or 10_000_000)
        except RuntimeError as e:
            # deadlocked/budget-stalled elements are isolated, same as
            # quarantine: report them, keep the finished elements' results
            stalled = [
                fleet.element_ids[j]
                for j in np.flatnonzero(~fleet.done_mask())
            ]
            print(f"sweep: {e} — isolating", file=sys.stderr)
        wall = time.perf_counter() - t0

    from ..stats.report import write_report

    counters = fleet.counters
    cycles = fleet.cycles
    if ns.report_dir:
        os.makedirs(ns.report_dir, exist_ok=True)
    total_ins = 0
    for j in range(fleet.n_elements):
        i = fleet.element_ids[j]  # caller-side index (quarantine-stable)
        ec = {k: v[j] for k, v in counters.items()}
        ins = int(ec["instructions"].sum())
        total_ins += ins
        detail = {
            "engine": "fleet",
            "fleet_index": i,
            "n_cores": cfg.n_cores,
            "instructions": ins,
            "max_core_cycles": int(cycles[j].max()),
            "overrides": ovs[i],
            "wall_s": round(wall, 3),
            "noc_msgs": int(ec["noc_msgs"].sum()),
        }
        if i in stalled:
            detail["status"] = "stalled"
        print(
            json.dumps(
                {
                    "metric": "simulated_MIPS",
                    "value": round(ins / wall / 1e6, 3),
                    "unit": "MIPS",
                    "detail": detail,
                }
            )
        )
        if ns.report_dir:
            path = os.path.join(ns.report_dir, f"element_{i}.txt")
            write_report(
                path, fleet.elem_cfgs[j], ec, cycles[j], wall_s=wall,
                per_core_limit=ns.per_core_limit,
                title=f"primesim_tpu fleet element {i}",
            )
            print(f"report written to {path}", file=sys.stderr)
    # fan the deduplicated twins' reports out: identical inputs give
    # identical results, copied from the element that actually simulated
    # (dedup_of names it); they don't add to the aggregate — no extra
    # instructions were retired on their behalf
    for i, twin in sorted(dup_of_caller.items()):
        jt = fleet.element_ids.index(twin)
        ec = {k: v[jt] for k, v in counters.items()}
        ins = int(ec["instructions"].sum())
        detail = {
            "engine": "fleet",
            "fleet_index": i,
            "n_cores": cfg.n_cores,
            "instructions": ins,
            "max_core_cycles": int(cycles[jt].max()),
            "overrides": ovs[i],
            "wall_s": round(wall, 3),
            "noc_msgs": int(ec["noc_msgs"].sum()),
            "dedup_of": twin,
        }
        if twin in stalled:
            detail["status"] = "stalled"
        print(
            json.dumps(
                {
                    "metric": "simulated_MIPS",
                    "value": round(ins / wall / 1e6, 3),
                    "unit": "MIPS",
                    "detail": detail,
                }
            )
        )
        if ns.report_dir:
            path = os.path.join(ns.report_dir, f"element_{i}.txt")
            write_report(
                path, fleet.elem_cfgs[jt], ec, cycles[jt], wall_s=wall,
                per_core_limit=ns.per_core_limit,
                title=f"primesim_tpu fleet element {i} (dedup of {twin})",
            )
            print(f"report written to {path}", file=sys.stderr)
    agg_detail = {
        "engine": "fleet",
        "n_elements": fleet.n_elements,
        "n_cores": cfg.n_cores,
        "instructions": total_ins,
        "wall_s": round(wall, 3),
    }
    if dup_of_caller:
        agg_detail["deduplicated"] = sorted(dup_of_caller)
    if quarantined:
        agg_detail["quarantined"] = [i for i, _ in quarantined]
    if stalled:
        agg_detail["stalled"] = stalled
    print(
        json.dumps(
            {
                "metric": "fleet_aggregate_MIPS",
                "value": round(total_ins / wall / 1e6, 3),
                "unit": "MIPS",
                "detail": agg_detail,
            }
        )
    )
    if rec is not None:
        tl = rec.timeline_summary()
        if tl:
            print(
                json.dumps(
                    {
                        "metric": "obs_timeline",
                        "value": tl["chunks"],
                        "unit": "chunks",
                        "detail": tl,
                    }
                )
            )
        _finalize_obs(rec)
    _emit_exec_cache_line(cache)
    if quarantined or stalled:
        # partial success is a distinct, scriptable outcome: the healthy
        # elements' results are real (exit 0 would hide the casualties,
        # exit 1 would discard the survivors)
        print(
            f"sweep: partial — {len(quarantined)} quarantined, "
            f"{len(stalled)} stalled of "
            f"{fleet.n_elements + len(quarantined)} elements",
            file=sys.stderr,
        )
        return 3
    return 0


def cmd_worker(ns) -> int:
    """Pool worker process (DESIGN.md §17): lease work units from a
    `sweep --workers` coordinator, simulate them under per-unit element
    checkpoints + heartbeats, ack results. Normally spawned BY the
    coordinator; running one by hand joins an in-flight campaign (that
    is the elastic part)."""
    from ..pool.worker import run_worker

    _activate_exec_cache(ns)  # engines consult the process-global cache
    return run_worker(
        ns.connect,
        ns.worker_id,
        warm_cache=ns.warm_cache == "on",
        reconnect_timeout_s=ns.reconnect_timeout,
        crash_after_chunks=ns.crash_after_chunks,
        idle_exit_s=ns.idle_exit,
        overlap=getattr(ns, "overlap", "off") == "on",
    )


def cmd_coordinator(ns) -> int:
    """Standalone dynamic-mode pool coordinator (DESIGN.md §18): the
    lease/heartbeat/ack bookkeeper for an elastic serving fleet.
    Normally spawned by `primetpu serve --pool-dir`; run by hand for a
    shared pool several front-ends dispatch into. SIGTERM/SIGINT close
    the socket and flush the unit ledger; kill -9 at any instant is
    recoverable — restarting over the same --pool-dir replays every
    enqueued unit, adopts acked results, and re-adopts live worker
    leases by heartbeat epoch."""
    import os
    import signal as _signal

    from ..pool.coordinator import PoolCoordinator
    from ..serve.protocol import socket_alive

    sock = ns.socket or os.path.join(ns.pool_dir, "pool.sock")
    if socket_alive(sock):
        # Probe BEFORE constructing: __init__ replays the shared ledger
        # and journals a recovery note, which a losing standby must not
        # spam into the live coordinator's journal.
        print(
            f"coordinator: a live coordinator already owns {sock}",
            file=sys.stderr,
        )
        return 1

    rec = _build_recorder(ns)
    coord = PoolCoordinator(
        [],
        pool_dir=ns.pool_dir,
        socket_path=ns.socket,
        lease_ttl_s=ns.lease_ttl,
        poison_threshold=ns.poison_threshold,
        hedge=ns.hedge == "on",
        obs=rec,
        dynamic=True,
        attest=getattr(ns, "attest", "off") or "off",
        audit_rate=float(getattr(ns, "audit_rate", 0.0) or 0.0),
    )
    try:
        coord.start()
    except RuntimeError as e:  # lost the bind race to another standby
        print(f"coordinator: {e}", file=sys.stderr)
        return 1
    pid_path = os.path.join(ns.pool_dir, "coordinator.pid")
    with open(pid_path, "w") as f:
        f.write(str(os.getpid()))
    stop = {"flag": False}

    def _term(signum, frame):
        stop["flag"] = True

    try:
        _signal.signal(_signal.SIGTERM, _term)
        _signal.signal(_signal.SIGINT, _term)
    except ValueError:
        pass
    r = coord.recovered
    print(
        f"coordinator: listening on {coord.socket_path} "
        f"(recovered units={r.get('units_respawned', 0)} "
        f"results={r.get('results_adopted', 0)} "
        f"leases={r.get('leases_readopted', 0)})",
        file=sys.stderr,
    )
    try:
        while not stop["flag"]:
            coord.tick()
            time.sleep(0.2)
    finally:
        coord.close()
        try:
            os.unlink(pid_path)
        except OSError:
            pass
        _finalize_obs(rec)
        print(
            f"coordinator: closed ({json.dumps(coord.pool_report())})",
            file=sys.stderr,
        )
    return 0


def cmd_synth(ns) -> int:
    tr = _parse_synth(ns.spec, ns.cores, ns.fold)
    tr.save(ns.out)
    print(
        f"wrote {ns.out}: {tr.n_cores} cores x {tr.max_len} events "
        f"({tr.total_instructions():,} instructions)",
        file=sys.stderr,
    )
    return 0


def cmd_info(ns) -> int:
    print(_load_config(ns.config).to_json())
    return 0


def cmd_calibrate(ns) -> int:
    """Fit traced timing knobs to a published microbenchmark table
    (DESIGN.md §25): coordinate-descent pattern search where every
    candidate set runs as ONE constant-shape fleet — the whole fit
    compiles once per geometry. Emits one `calibrate_residual` JSON line
    per table entry plus a final `calibrate_fit` line; `--selftest`
    replaces the observed column with values simulated at ground-truth
    knobs and asserts the fit recovers them (exit 1 if not)."""
    from ..calib.fit import (
        FIT_KEYS_DEFAULT, apply_fit, check_fit_keys, fit, knob_start,
        synthesize_observed,
    )
    from ..calib.table import load_table

    cfg = _load_config(ns.config)
    table = load_table(ns.table)
    fit_keys = (
        check_fit_keys(k.strip() for k in ns.fit.split(","))
        if ns.fit else FIT_KEYS_DEFAULT
    )
    truth = None
    if ns.selftest:
        # ground truth: explicit --truth overrides, else a deterministic
        # perturbation of the config's own knobs (so the search must
        # genuinely move to recover them)
        truth = (
            {k: int(v) for k, v in _parse_vary(ns.truth).items()}
            if ns.truth
            else {
                k: v + max(1, v // 2)
                for k, v in knob_start(cfg, fit_keys).items()
            }
        )
        check_fit_keys(truth.keys())
        table = synthesize_observed(
            cfg, table, truth, chunk_steps=ns.chunk_steps
        )
    t0 = time.perf_counter()
    res = fit(
        cfg, table, fit_keys=fit_keys, max_rounds=ns.rounds,
        chunk_steps=ns.chunk_steps,
        log=(lambda s: print(f"calibrate: {s}", file=sys.stderr))
        if ns.verbose else None,
    )
    wall = time.perf_counter() - t0
    for name, sim, obs, r in res.residuals:
        print(
            json.dumps(
                {
                    "metric": "calibrate_residual",
                    "value": round(r, 6),
                    "unit": "relative",
                    "detail": {
                        "entry": name,
                        "simulated": round(sim, 4),
                        "observed": round(obs, 4),
                        "table": table.name,
                    },
                }
            )
        )
    detail = {
        "table": table.name,
        "fit_keys": list(fit_keys),
        "knobs": res.knobs,
        "start": res.start,
        "rounds": res.rounds,
        "fleet_runs": res.fleet_runs,
        "batch": res.batch,
        "wall_s": round(wall, 3),
    }
    if truth is not None:
        detail["truth"] = truth
        # exact knob equality is informational (latency knobs can trade
        # off degenerately, e.g. link vs router on fixed-hop entries);
        # the self-test CONTRACT is ~zero residual at the fitted point
        detail["recovered"] = all(
            res.knobs[k] == v for k, v in truth.items()
        )
        detail["selftest_ok"] = res.cost <= ns.tol
    print(
        json.dumps(
            {
                "metric": "calibrate_fit",
                "value": round(res.cost, 8),
                "unit": "sum_sq_rel_residual",
                "detail": detail,
            }
        )
    )
    if ns.out:
        report = res.report()
        report["table"] = table.name
        report["config"] = apply_fit(cfg, res.knobs).to_json()
        if truth is not None:
            report["truth"] = truth
        with open(ns.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"calibration report written to {ns.out}", file=sys.stderr)
    if truth is not None and not detail["selftest_ok"]:
        print(
            f"calibrate: SELFTEST FAILED — residual cost {res.cost:.3g} "
            f"> tol {ns.tol:.3g} (truth {truth}, fitted "
            f"{ {k: res.knobs[k] for k in truth} })",
            file=sys.stderr,
        )
        return 1
    return 0


def cmd_lint(ns) -> int:
    from ..analysis.lint import render_human, render_json, run_lint

    res = run_lint(
        paths=ns.paths or None,
        root=ns.root,
        baseline_path=ns.baseline,
        select=ns.select or None,
    )
    if ns.format == "json":
        print(render_json(res))
    else:
        print(render_human(res))
    return 0 if res.clean else 1


def cmd_fsck(ns) -> int:
    from ..analysis.errors import FsckCorrupt
    from ..analysis.fsck import (render_human, render_json, run_compare,
                                 run_fsck)

    if ns.compare:
        res = run_compare(ns.compare[0], ns.compare[1])
        where = res.root
    else:
        if not ns.dir:
            raise FsckCorrupt("fsck needs DIR (or --compare DIR_A DIR_B)")
        res = run_fsck(ns.dir, repair=ns.repair)
        where = ns.dir
    if ns.format == "json":
        print(render_json(res))
    else:
        print(render_human(res))
    if not res.clean:
        first = res.corrupt[0]
        raise FsckCorrupt(
            f"{len(res.corrupt)} corrupt artifact finding(s) under "
            f"{where} (first: {first.path}: {first.detail})",
            path=first.path, n_corrupt=len(res.corrupt),
        )
    return 0


def cmd_audit(ns) -> int:
    """Offline replay audit (DESIGN.md §24): re-execute a pool
    campaign's DONE units from their journaled specs and compare the
    recomputed fingerprint-chain heads against the ledger's acked
    heads, its retained hedged-twin/held evidence, and the surviving
    element checkpoints. Works on a kill -9'd pool dir — the ledger is
    read with fsck's read-only reader, nothing is mutated."""
    from ..attest.audit import run_audit
    from ..attest.errors import AttestationError

    res = run_audit(ns.dir, unit_ids=ns.unit)
    for v in res["units"]:
        print(json.dumps(v))
    s = res["summary"]
    print(
        f"audit: {s['audited']} unit(s) replayed — {s['ok']} ok, "
        f"{s['mismatch']} mismatch, {s['adjudicated']} adjudicated, "
        f"{s['incomparable']} incomparable, {s['skipped']} skipped",
        file=sys.stderr,
    )
    if s["mismatch"]:
        first = next(v for v in res["units"] if v["status"] == "mismatch")
        raise AttestationError(
            f"{s['mismatch']} unit(s) fail offline replay audit under "
            f"{ns.dir} (first: {first['unit_id']})",
            site="audit.replay", unit=first["unit_id"],
        )
    return 0


def cmd_chaos(ns) -> int:
    """Seeded crash campaign (DESIGN.md §20): N trials of the serve
    stack under generated fault plans, invariants machine-checked after
    each; violations shrink to a minimal replayable artifact. Exit 0
    clean, 3 on any violation."""
    from ..chaos import campaign as C

    cfg = _load_config(ns.config) if ns.config else None
    if ns.plan:
        res = C.replay_artifact(ns.plan, cfg=cfg)
        print(json.dumps(res.as_dict(), indent=2, sort_keys=True))
        return 0 if res.ok else 3

    def progress(seed, res):
        if ns.verbose:
            print(
                f"trial seed={seed} "
                f"{'ok' if res.ok else 'VIOLATION'} "
                f"fired={len(res.injected)} restarts={res.restarts}",
                file=sys.stderr,
            )

    report = C.run_campaign(
        n_trials=ns.trials,
        seed0=ns.seed,
        classes=tuple(ns.classes.split(",")),
        cfg=cfg,
        artifact_dir=ns.out,
        max_events=ns.max_events,
        progress=progress,
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 3


def _parse_buckets(spec: str):
    """'SLOTSxPAGES[,SLOTSxPAGES...]' -> ((slots, pages), ...) — the
    serving fleet's paged capacity ladder (serve.scheduler)."""
    out = []
    for part in spec.split(","):
        s, x, p = part.partition("x")
        if not x or not s.isdigit() or not p.isdigit() \
                or int(s) < 1 or int(p) < 1:
            raise SystemExit(
                f"bad --buckets entry {part!r} (want SLOTSxPAGES, e.g. 6x1)"
            )
        out.append((int(s), int(p)))
    return tuple(out)


def cmd_serve(ns) -> int:
    """Start the continuous-batching simulation daemon (DESIGN.md §14):
    one compiled fleet program per capacity bucket, jobs spliced into
    slots as elements retire, WAL-journaled so kill -9 loses nothing.
    SIGTERM drains (checkpoint + exit 75 when work remains); SIGHUP
    reloads --config's fault schedule (same geometry only)."""
    cfg = _apply_faults(ns, _apply_step_impl(ns, _load_config(ns.config)))
    from ..serve.quota import TenantQuota
    from ..serve.server import PrimeServer

    # process-global AOT cache: in-process scheduler buckets compile/
    # deserialize through it; dispatch mode propagates the flag to the
    # autoscaled workers' argv (serve/dispatch.py)
    _activate_exec_cache(ns)
    rec = _build_recorder(ns)
    if ns.tcp and ns.socket:
        raise SystemExit("--tcp and --socket are mutually exclusive")
    if getattr(ns, "devices", 0) and not ns.pool_dir:
        raise SystemExit(
            "serve: --devices needs dispatch mode (--pool-dir): sharded "
            "fleets live on pool workers, not in the front-end process"
        )
    if getattr(ns, "devices", 0):
        from ..parallel.sharding import validate_devices

        validate_devices(cfg, ns.devices)
    replicas = [t.strip() for t in (ns.replicas or "").split(",")
                if t.strip()]
    if ns.standby_of:
        # hot standby (DESIGN.md §21): tail the replicas while the
        # incumbent lives; once it stays dead past the grace window,
        # adopt the highest-epoch replica chain and fall through to serve
        # the new primary — whose begin_epoch() fences the old one
        if not replicas:
            raise SystemExit("--standby-of requires --replicas")
        from ..serve.replicate import Standby

        sb = Standby(ns.standby_of, replicas, ns.state_dir,
                     grace_s=ns.takeover_grace)
        print(
            f"serve: standby of {ns.standby_of} "
            f"(replicas={','.join(replicas)}, "
            f"grace={ns.takeover_grace}s)",
            file=sys.stderr,
        )
        report = sb.wait_for_takeover()
        print(
            f"serve: PROMOTING — adopted chain from {report['source']} "
            f"(tip seq={report['tip']['seq']}, "
            f"{report['reachable']} replica(s) reachable)",
            file=sys.stderr,
        )
    server = PrimeServer(
        cfg,
        state_dir=ns.state_dir,
        socket_path=ns.tcp or ns.socket,
        buckets=_parse_buckets(ns.buckets),
        chunk_steps=ns.chunk_steps,
        max_queue=ns.max_queue,
        checkpoint_every_s=ns.checkpoint_wall,
        config_path=ns.config,
        idle_exit_s=ns.idle_exit,
        obs=rec,
        warm_cache=ns.warm_cache == "on",
        pool_dir=ns.pool_dir,
        max_workers=ns.workers,
        lease_ttl_s=ns.lease_ttl,
        quota=TenantQuota.parse(ns.quota) if ns.quota else None,
        replicas=replicas or None,
        quorum=ns.quorum,
        quorum_policy=ns.quorum_policy,
        devices=getattr(ns, "devices", 0) or 0,
        attest=getattr(ns, "attest", "off") or "off",
        audit_rate=float(getattr(ns, "audit_rate", 0.0) or 0.0),
    )
    # bind before the readiness line so `--tcp HOST:0` prints the real
    # kernel-assigned port (tests and scripts scrape this line)
    target = server.bind()
    mode = f"dispatch->{ns.pool_dir}" if ns.pool_dir else "local"
    if server.repl is not None:
        mode += (f", replicated x{len(server.repl.links)} "
                 f"quorum={server.repl.quorum} "
                 f"epoch={server.repl.epoch}")
    print(
        f"serve: listening on {target} ({mode}, "
        f"recovered={server.recovered['jobs_requeued']} job(s))",
        file=sys.stderr,
    )
    rc = server.serve_forever()
    if ns.report:
        import numpy as np

        from ..stats.counters import COUNTER_NAMES
        from ..stats.report import write_report

        # the aggregate SERVICE report: per-core counter/cycle axes are
        # not meaningful across heterogeneous jobs, so they render zero
        # and the SERVICE section carries the data
        write_report(
            ns.report, cfg,
            {k: np.zeros(cfg.n_cores, np.int64) for k in COUNTER_NAMES},
            np.zeros(cfg.n_cores, np.int64),
            title="primetpu serve",
            service=server.sched.service_report(),
            timeline=rec.timeline_summary() if rec is not None else None,
        )
        print(f"report written to {ns.report}", file=sys.stderr)
    _finalize_obs(rec)
    print(
        f"serve: drained rc={rc} "
        f"({json.dumps(server.sched.service_report())})",
        file=sys.stderr,
    )
    return rc


def cmd_replica(ns) -> int:
    """Run one journal follower (DESIGN.md §21): a byte-blind segment
    store behind a `repl.*` listener. Point a primary's `--replicas` at
    it; a standby promotes from it. SIGTERM stops cleanly — the chain
    on disk IS the durable state, there is nothing to drain."""
    import os
    import signal as _signal

    from ..serve.replicate import ReplicaServer

    if ns.tcp and ns.socket:
        raise SystemExit("--tcp and --socket are mutually exclusive")
    server = ReplicaServer(ns.dir, ns.tcp or ns.socket
                           or os.path.join(ns.dir, "replica.sock"))
    target = server.bind()
    tip = server.store.tip()
    print(
        f"replica: listening on {target} (dir={ns.dir}, "
        f"epoch={server.epoch}, tip seq={tip['seq']})",
        file=sys.stderr,
    )

    def _stop(signum, frame):
        server.die()

    try:
        _signal.signal(_signal.SIGTERM, _stop)
        _signal.signal(_signal.SIGINT, _stop)
    except ValueError:
        pass
    server.serve_forever()
    server.shutdown()
    return 0


def cmd_submit(ns) -> int:
    """Submit one job to a running daemon; with --wait, block for the
    terminal state and print the full result record."""
    from ..serve.client import ServeClient, ServeError

    cli = ServeClient(ns.socket)
    overrides = {}
    for spec in ns.vary or []:
        overrides.update(_parse_vary(spec))
    try:
        job = cli.submit(
            trace_path=ns.trace,
            synth=ns.synth,
            overrides=overrides,
            fold=ns.fold,
            deadline_s=ns.deadline,
            max_steps=ns.max_steps or 10_000_000,
            priority=ns.priority,
            client=ns.client,
            retries=ns.retries,
        )
        if ns.wait:
            job = cli.wait(job["job_id"], timeout_s=ns.timeout)
    except ServeError as e:
        out = {"ok": False, "error": e.error}
        if e.retry_after_s is not None:
            out["retry_after_s"] = e.retry_after_s
        print(json.dumps(out))
        return 4 if e.retry_after_s is not None else 1
    except OSError as e:
        from ..serve.protocol import error_obj

        print(json.dumps({"ok": False, **error_obj(e)}))
        return 1
    print(json.dumps({"ok": True, "job": job}))
    if ns.wait and job["state"] != "DONE":
        return 1
    return 0


def _watch_line(h: dict) -> str:
    """One live status line from a health reply (serve-status --watch)."""
    jobs = h.get("jobs", {})
    slots = h.get("slots", {})
    lat = h.get("latency_s") or {}
    age = h.get("last_dispatch_age_s")
    parts = [
        time.strftime("%H:%M:%S"),
        f"q={h.get('queue_depth', 0)}",
        f"slots={slots.get('occupied', 0)}/{slots.get('total', 0)}",
        f"run={jobs.get('RUNNING', 0)}",
        f"done={h.get('completed', 0)}",
        f"mips={h.get('aggregate_mips', 0.0)}",
        f"p50={lat.get('p50') if lat.get('p50') is not None else '-'}",
        f"disp={f'{age}s ago' if age is not None else 'never'}",
        f"up={h.get('uptime_s', 0)}s",
    ]
    if h.get("draining"):
        parts.append("DRAINING")
    return "  ".join(parts)


def cmd_serve_status(ns) -> int:
    """Query a running daemon: health (default), --jobs listing,
    --metrics (Prometheus text), --watch (live one-line refresh), or
    --drain (ask it to finish the queue and exit)."""
    from ..serve.client import ServeClient, ServeError

    cli = ServeClient(ns.socket)
    try:
        if ns.drain:
            print(json.dumps(cli.drain()))
        elif ns.jobs:
            print(json.dumps(cli.status()))
        elif ns.metrics:
            sys.stdout.write(cli.metrics())
        elif ns.watch:
            from ..util.backoff import DecorrelatedJitter

            n = 0
            down_since = None
            failed_polls = 0
            jit = DecorrelatedJitter(base=min(ns.interval, 0.5),
                                     cap=max(ns.interval * 4, 2.0))
            while True:
                # the client already retried once on connect failure;
                # a still-dead target prints DOWN and keeps watching
                # under jittered backoff (the daemon may be mid-restart
                # or failing over to a standby — a wall of watchers must
                # not stampede the reborn listener in the same instant)
                try:
                    line = _watch_line(cli.health())
                    if down_since is not None:
                        line += (
                            f"  [RECOVERED after "
                            f"{time.monotonic() - down_since:.1f}s "
                            f"({failed_polls} failed poll(s)) "
                            f"via {cli.target}]"
                        )
                        down_since = None
                        failed_polls = 0
                        jit.reset()
                except (ServeError, OSError) as e:
                    down_since = down_since or time.monotonic()
                    failed_polls += 1
                    line = (
                        f"{time.strftime('%H:%M:%S')}  "
                        f"DOWN {cli.target} ({type(e).__name__})"
                    )
                print(line, flush=True)
                n += 1
                if ns.count and n >= ns.count:
                    break
                time.sleep(jit.next_delay() if down_since is not None
                           else ns.interval)
        else:
            print(json.dumps(cli.health()))
    except KeyboardInterrupt:
        return 0
    except ServeError as e:
        print(json.dumps({"ok": False, "error": e.error}))
        return 1
    except OSError as e:
        from ..serve.protocol import error_obj

        print(json.dumps({"ok": False, **error_obj(e)}))
        return 1
    return 0


def _add_resilience_flags(sp) -> None:
    """Shared run/sweep resilience surface (DESIGN.md §10): any of these
    flags switches the command onto the supervised chunk-committed path
    (sim.supervisor.RunSupervisor) — results stay bit-exact."""
    sp.add_argument(
        "--checkpoint-dir", metavar="DIR",
        help="rotating-snapshot directory (ckpt-<seq>.npz, atomic + "
             "CRC-verified); enables checkpointing and --resume",
    )
    sp.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="K",
        help="checkpoint every K committed chunks (needs --checkpoint-dir)",
    )
    sp.add_argument(
        "--checkpoint-wall", type=float, default=0.0, metavar="SEC",
        help="checkpoint when SEC wall-seconds passed since the last one "
             "(needs --checkpoint-dir; combines with --checkpoint-every)",
    )
    sp.add_argument(
        "--keep-snapshots", type=int, default=3, metavar="N",
        help="rotating snapshots retained in --checkpoint-dir (default 3)",
    )
    sp.add_argument(
        "--resume", action="store_true",
        help="restore the newest VALID snapshot from --checkpoint-dir "
             "(corrupt ones are skipped; config+trace fingerprints are "
             "verified) and continue — bit-exact with an uninterrupted run",
    )
    sp.add_argument(
        "--guard", choices=("off", "warn", "fail"), default="off",
        help="post-chunk invariant guard (MESI/directory consistency, "
             "clock window, monotone counters): warn logs violations, "
             "fail stops BEFORE checkpointing the bad state",
    )
    sp.add_argument(
        "--max-retries", type=int, default=4, metavar="N",
        help="retries per chunk on transient device failures (exponential "
             "backoff; OOM halves chunk_steps; last resort: CPU backend)",
    )


def _add_obs_flags(sp) -> None:
    """Shared run/sweep/serve telemetry surface (DESIGN.md §15). `off`
    keeps the fused dispatch paths and is bit-exact with a build that
    has no obs layer at all; `basic` adds the per-chunk metric ring;
    `full` adds the Chrome-trace flight recorder."""
    sp.add_argument(
        "--obs", choices=("off", "basic", "full"), default="off",
        help="telemetry level: off (default; fused dispatch, bit-exact), "
             "basic (per-chunk metric time-series, chunked dispatch), "
             "full (basic + flight-recorder timeline)",
    )
    sp.add_argument(
        "--metrics-out", metavar="FILE",
        help="dump the per-chunk metric series as JSONL at exit "
             "(needs --obs basic|full)",
    )
    sp.add_argument(
        "--trace-out", metavar="FILE",
        help="write the Chrome trace-event timeline at exit — load it "
             "in Perfetto / chrome://tracing (needs --obs full)",
    )
    sp.add_argument(
        "--obs-capacity", type=int, default=4096, metavar="N",
        help="metric ring-buffer size in chunks; older samples drop "
             "first (default 4096)",
    )


def _build_recorder(ns):
    """--obs flags -> obs.Recorder (or None at level off, which is what
    keeps every engine telemetry branch dead)."""
    level = getattr(ns, "obs", "off")
    if getattr(ns, "trace_out", None) and level != "full":
        raise SystemExit(
            "--trace-out requires --obs full (the flight recorder only "
            "runs at full)"
        )
    if getattr(ns, "metrics_out", None) and level == "off":
        raise SystemExit("--metrics-out requires --obs basic|full")
    if level == "off":
        return None
    from ..obs import Recorder

    return Recorder(
        level,
        capacity=ns.obs_capacity,
        trace_path=getattr(ns, "trace_out", None),
        metrics_path=getattr(ns, "metrics_out", None),
    )


def _finalize_obs(rec) -> None:
    """Write the recorder's output files (idempotent; runs on the
    normal, preempted, and drained exit paths alike)."""
    if rec is None:
        return
    for kind, (path, n) in rec.finalize().items():
        print(f"obs: {kind} written to {path} ({n} records)",
              file=sys.stderr)


def _add_exec_flags(sp, overlap: bool = True) -> None:
    """Shared run/sweep/worker/serve compile-once surface (DESIGN.md
    §23). Both default OFF and off is byte-identical to a build without
    the exec-cache layer at all."""
    sp.add_argument(
        "--exec-cache", choices=("on", "off"), default="off",
        help="consult/populate the on-disk AOT executable cache "
             "($PRIMETPU_CACHE_DIR/exec): a warm process deserializes "
             "the compiled program instead of paying trace+lower+XLA "
             "compile; corrupt/stale entries degrade to recompile",
    )
    sp.add_argument(
        "--cache-budget", type=int, default=None, metavar="BYTES",
        help="shared byte budget for the governed artifact pool (warm-"
             "state cache + AOT exec cache; DESIGN.md §26): LRU pruning "
             "and the disk-pressure evict ladder both honor it; takes "
             "precedence over $PRIMETPU_CACHE_MAX_BYTES (default: env "
             "var, then 2 GiB)",
    )
    if overlap:
        sp.add_argument(
            "--overlap", choices=("on", "off"), default="off",
            help="overlapped chunk dispatch: enqueue chunk k+1 before "
                 "host-side durability work (journal fsync, checkpoint "
                 "write, obs commit) so the device computes while the "
                 "host syncs; bit-exact, chunked paths only",
        )


def _activate_exec_cache(ns):
    """--exec-cache on -> the process-global cache (engines, supervisor
    resume and serve buckets consult `exec_cache.active()`, so one flag
    covers every compile site in the process)."""
    from ..sim import exec_cache
    from ..util import diskpressure

    if getattr(ns, "cache_budget", None) is not None:
        diskpressure.configure(budget_bytes=ns.cache_budget)
    if getattr(ns, "exec_cache", "off") == "on":
        return exec_cache.configure(True)
    return exec_cache.configure(False)


def _emit_exec_cache_line(cache) -> None:
    """The scriptable exec-cache record (CI parses hits/misses and
    compile_wall_s from it; the structured fallback warnings ride in
    detail). Printed only when --exec-cache on, keeping default-off
    output byte-identical to the pre-cache CLI."""
    if cache is None:
        return
    detail = dict(cache.stats)
    detail["compile_wall_s"] = round(detail["compile_wall_s"], 3)
    detail["load_wall_s"] = round(detail["load_wall_s"], 3)
    if cache.warnings:
        detail["warnings"] = cache.warnings
    print(
        json.dumps(
            {
                "metric": "exec_cache",
                "value": detail["hits"],
                "unit": "hits",
                "detail": detail,
            }
        )
    )


def _emit_ttfs_line(cache, t_start: float) -> None:
    """First-class time-to-first-step metric: wall time from command
    entry until the first chunk has executed (the warm-up dispatch),
    split into compile vs deserialize. Cold runs record a miss, warm
    runs a hit with compile_wall_s ~ 0."""
    if cache is None:
        return
    print(
        json.dumps(
            {
                "metric": "time_to_first_step",
                "value": round(time.perf_counter() - t_start, 3),
                "unit": "s",
                "detail": {
                    "cold": cache.stats["misses"] > 0,
                    "compile_wall_s": round(
                        cache.stats["compile_wall_s"], 3
                    ),
                    "load_wall_s": round(cache.stats["load_wall_s"], 3),
                },
            }
        )
    )


def _add_fault_flags(sp) -> None:
    """Shared run/sweep fault-injection surface (DESIGN.md §12)."""
    sp.add_argument(
        "--fault-schedule", metavar="FILE",
        help="JSON fault schedule (events + flip/DUE rates + policies); "
             "arms the deterministic fault model (DESIGN.md §12)",
    )
    sp.add_argument(
        "--fault-seed", type=int, default=None, metavar="N",
        help="seed for the counter-based fault PRNG (traced: sweeping it "
             "never recompiles; default 0)",
    )


def _add_attest_flags(sp, audit: bool = True) -> None:
    sp.add_argument(
        "--attest", choices=("off", "chain"), default="off",
        help="result integrity (DESIGN.md §24): fingerprint-chain every "
             "committed chunk, compare hedged-twin results instead of "
             "discarding the loser, and verify worker toolchains at "
             "lease grant (default off — bit-exact with today)",
    )
    if audit:
        sp.add_argument(
            "--audit-rate", type=float, default=0.0, metavar="P",
            help="(--attest chain) re-dispatch this fraction of DONE "
                 "units to a different worker and compare chain heads "
                 "(deterministic per-unit sampling; default 0)",
        )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="primetpu",
        description="TPU-native manycore architecture simulator (PriME-class)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="simulate a trace on a machine config")
    r.add_argument("config", help="machine config (.json or reference-schema .xml)")
    r.add_argument(
        "--trace", action="append",
        help="PTPU trace file (repeat for a MULTIPROGRAMMED run: each "
             "program's cores/addresses/sync multiplex into one machine)",
    )
    r.add_argument("--synth", help="synthetic workload spec name[:k=v,...]")
    r.add_argument(
        "--fold", action="store_true", help="fold INS batches into pre fields"
    )
    r.add_argument("--engine", choices=("jax", "golden"), default="jax")
    r.add_argument(
        "--step-impl", choices=("xla", "pallas"), default=None,
        help="step implementation (jax engine): 'pallas' routes phase "
             "1/4 + the reductions through the fused VMEM step kernels "
             "(kernels/, DESIGN.md §11); default: the config's step_impl",
    )
    r.add_argument("--chunk-steps", type=int, default=256)
    r.add_argument(
        "--max-steps", type=int, default=None,
        help="step budget (default: 10M, or event-count-derived when "
             "streaming)",
    )
    r.add_argument("--report", help="write text report to this path")
    r.add_argument("--per-core-limit", type=int, default=64)
    r.add_argument(
        "--debug-invariants", action="store_true",
        help="check DESIGN.md machine invariants after every chunk "
             "(jax engine; slower, chunked dispatch)",
    )
    r.add_argument(
        "--xprof",
        help="write a JAX profiler trace of the run to this directory "
             "(jax engine; inspect with xprof/tensorboard)",
    )
    r.add_argument(
        "--stream-window", type=int, default=0, metavar="N",
        help="stream the trace through N-event windows (bounded device "
             "memory; bit-exact vs preloaded; for traces larger than HBM)",
    )
    r.add_argument(
        "--mmap", action="store_true",
        help="memory-map the trace file (pair with --stream-window for "
             "traces larger than host memory)",
    )
    r.add_argument(
        "--devices", type=int, default=0, metavar="N",
        help="shard the simulated machine over the first N jax devices "
             "(cores/L1s by core, LLC/directory by bank; jax engine)",
    )
    r.add_argument(
        "--ingest-workers", type=int, default=0, metavar="K",
        help="(--stream-window) pipeline the window fill MPMD-style: K "
             "pool worker processes ingest trace segments over the lease "
             "protocol, ahead of the (supervised) simulation in this "
             "process (DESIGN.md §22)",
    )
    r.add_argument(
        "--seg-events", type=int, default=0, metavar="L",
        help="(--ingest-workers) events/core per ingest segment "
             "(default: max(--stream-window, 4096))",
    )
    r.add_argument(
        "--pool-dir", default=None, metavar="DIR",
        help="(--ingest-workers) segment files + ingest lease ledger "
             "live here; re-running with the same DIR re-uses segments "
             "already ingested (default: a throwaway temp dir)",
    )
    _add_resilience_flags(r)
    _add_fault_flags(r)
    _add_obs_flags(r)
    _add_exec_flags(r)
    _add_attest_flags(r, audit=False)
    r.set_defaults(fn=cmd_run)

    w = sub.add_parser(
        "sweep",
        help="fan timing overrides and/or traces into ONE batched fleet "
             "run (one compiled program; one report per element)",
    )
    w.add_argument("config", help="machine config (.json or .xml)")
    w.add_argument(
        "--trace", action="append",
        help="PTPU trace file (repeat for per-element traces)",
    )
    w.add_argument(
        "--synth", action="append",
        help="synthetic workload spec name[:k=v,...] (repeatable)",
    )
    w.add_argument(
        "--vary", action="append", metavar="K=V[,K=V...]",
        help="one fleet element's timing overrides (repeatable; keys: "
             "quantum, cpi, l1_lat, llc_lat, link_lat, router_lat, "
             "dram_lat, dram_service, contention_lat, fault_seed)",
    )
    w.add_argument(
        "--fold", action="store_true", help="fold INS batches into pre fields"
    )
    w.add_argument(
        "--step-impl", choices=("xla", "pallas"), default=None,
        help="step implementation for every fleet element (geometry-keyed "
             "like the rest of the jit key: the whole sweep still "
             "compiles once; timing knobs stay traced)",
    )
    w.add_argument("--chunk-steps", type=int, default=256)
    w.add_argument("--max-steps", type=int, default=None)
    w.add_argument(
        "--fork-prefix", default="off", metavar="auto|off|N",
        help="run each prefix-sharing class's shared prefix ONCE as a "
             "solo engine and fork it into the fleet slots (bit-exact; "
             "'auto' forks at the divergence point, an integer caps the "
             "prefix at N steps; default off)",
    )
    w.add_argument(
        "--warm-cache", choices=("on", "off"), default="off",
        help="consult/populate the on-disk warm-state cache "
             "($PRIMETPU_CACHE_DIR) for forked prefixes — a repeated "
             "campaign skips the prefix simulation entirely",
    )
    w.add_argument(
        "--report-dir", help="write per-element text reports to this directory"
    )
    w.add_argument("--per-core-limit", type=int, default=64)
    w.add_argument(
        "--strict", action="store_true",
        help="disable fleet fault isolation: any malformed element "
             "(unreadable trace, bad overrides) aborts the whole sweep "
             "instead of being quarantined into its own JSON line",
    )
    w.add_argument(
        "--devices", type=int, default=0, metavar="N",
        help="shard EVERY fleet element over the first N jax devices "
             "(shard x vmap, DESIGN.md §22: cores/L1s by core, LLC/"
             "directory by bank, under the element batch; still one "
             "compiled program per geometry); with --workers each worker "
             "owns a sharded fleet on its own mesh",
    )
    w.add_argument(
        "--workers", type=int, default=0, metavar="N",
        help="run the sweep as an elastic pooled campaign: a lease-based "
             "coordinator plus N worker processes; a crashed/OOM-killed "
             "worker's units re-dispatch and resume from their last "
             "checkpoint (DESIGN.md §17)",
    )
    w.add_argument(
        "--pool-dir", default=None, metavar="DIR",
        help="(--workers) lease ledger + per-unit checkpoints live here; "
             "restarting a killed campaign with the same DIR resumes it "
             "(default: a throwaway temp dir)",
    )
    w.add_argument(
        "--lease-ttl", type=float, default=10.0, metavar="SEC",
        help="(--workers) lease deadline; a worker missing heartbeats "
             "this long is presumed dead and its unit re-dispatches "
             "(default 10)",
    )
    w.add_argument(
        "--poison-threshold", type=int, default=2, metavar="K",
        help="(--workers) quarantine a unit after its lease expired "
             "under K DISTINCT workers (default 2)",
    )
    w.add_argument(
        "--hedge", choices=("on", "off"), default="on",
        help="(--workers) near campaign end, speculatively re-dispatch "
             "the slowest in-flight unit to an idle worker; first ack "
             "wins (default on)",
    )
    w.add_argument(
        "--report", metavar="PATH",
        help="(--workers) write a text report with the POOL section",
    )
    _add_attest_flags(w)
    _add_resilience_flags(w)
    _add_fault_flags(w)
    _add_obs_flags(w)
    _add_exec_flags(w)
    w.set_defaults(fn=cmd_sweep)

    k = sub.add_parser(
        "worker",
        help="pool worker: lease sweep work units from a `sweep "
             "--workers` coordinator socket (normally spawned by it; "
             "run by hand to elastically join a campaign)",
    )
    k.add_argument("--connect", required=True, metavar="SOCK",
                   help="coordinator unix socket path")
    k.add_argument("--worker-id", required=True, metavar="ID")
    k.add_argument(
        "--warm-cache", choices=("on", "off"), default="off",
        help="consult the on-disk warm-state cache for fresh units",
    )
    k.add_argument(
        "--reconnect-timeout", type=float, default=60.0, metavar="SEC",
        help="give up (exit 75) after the coordinator has been "
             "unreachable this long",
    )
    k.add_argument(
        "--crash-after-chunks", type=int, default=None,
        help=argparse.SUPPRESS,  # chaos-test hook: SIGKILL self at chunk N
    )
    k.add_argument(
        "--idle-exit", type=float, default=None, metavar="SEC",
        help="exit 0 after SEC seconds of continuous idle (no leases "
             "granted) — the elastic fleet's scale-down path",
    )
    _add_exec_flags(k)
    k.set_defaults(fn=cmd_worker)

    co = sub.add_parser(
        "coordinator",
        help="standalone dynamic-mode pool coordinator for an elastic "
             "serving fleet (normally spawned by `serve --pool-dir`; "
             "run by hand to share one pool across front-ends)",
    )
    co.add_argument(
        "--pool-dir", required=True, metavar="DIR",
        help="unit ledger + checkpoints + default socket live here; "
             "restarting with the same DIR replays every enqueued unit",
    )
    co.add_argument(
        "--socket", default=None, metavar="PATH|HOST:PORT",
        help="listen target (default: POOL_DIR/pool.sock; host:port "
             "listens on TCP, port 0 = kernel-assigned)",
    )
    co.add_argument(
        "--lease-ttl", type=float, default=10.0, metavar="SEC",
        help="re-dispatch a unit after SEC without a heartbeat",
    )
    co.add_argument(
        "--poison-threshold", type=int, default=3, metavar="N",
        help="quarantine a unit after it kills N workers",
    )
    co.add_argument(
        "--hedge", choices=("on", "off"), default="on",
        help="duplicate the straggler unit on idle workers (default on)",
    )
    _add_attest_flags(co)
    _add_obs_flags(co)
    co.set_defaults(fn=cmd_coordinator)

    c = sub.add_parser(
        "capture",
        help="run a pthread binary under the capture frontend and "
             "simulate it ONLINE (or write a trace with --out)",
    )
    c.add_argument("config", help="machine config (.json or .xml)")
    c.add_argument(
        "command", nargs="+",
        help="target command line (prefix with -- to separate flags)",
    )
    c.add_argument(
        "--out", help="write a PTPU trace instead of simulating online"
    )
    c.add_argument("--window", type=int, default=1024)
    c.add_argument("--report", help="write text report to this path")
    c.add_argument("--per-core-limit", type=int, default=64)
    c.set_defaults(fn=cmd_capture)

    s = sub.add_parser("synth", help="generate a synthetic PTPU trace file")
    s.add_argument("spec", help="generator spec name[:k=v,...]")
    s.add_argument("--cores", type=int, required=True)
    s.add_argument("--out", required=True)
    s.add_argument("--fold", action="store_true")
    s.set_defaults(fn=cmd_synth)

    i = sub.add_parser("info", help="parse + print a machine config")
    i.add_argument("config")
    i.set_defaults(fn=cmd_info)

    v = sub.add_parser(
        "serve",
        help="run the continuous-batching simulation daemon (jobs over a "
             "unix socket; WAL-journaled, crash-safe, drains on SIGTERM)",
    )
    v.add_argument("config", help="machine config (.json or .xml)")
    v.add_argument(
        "--state-dir", required=True, metavar="DIR",
        help="journal + per-job checkpoints + default socket live here; "
             "restarting with the same DIR resumes every unfinished job",
    )
    v.add_argument(
        "--socket", default=None, metavar="PATH",
        help="unix socket path (default: STATE_DIR/serve.sock)",
    )
    v.add_argument(
        "--tcp", default=None, metavar="HOST:PORT",
        help="listen on TCP instead of the unix socket (port 0 = "
             "kernel-assigned; the readiness line prints the real one)",
    )
    v.add_argument(
        "--pool-dir", default=None, metavar="DIR",
        help="dispatch mode: run jobs on an autoscaling pool-worker "
             "fleet over this pool directory (spawns a coordinator, or "
             "adopts one already listening — the standby-takeover path)",
    )
    v.add_argument(
        "--workers", type=int, default=2, metavar="N",
        help="dispatch mode: autoscale up to N worker processes "
             "(default 2)",
    )
    v.add_argument(
        "--lease-ttl", type=float, default=10.0, metavar="SEC",
        help="dispatch mode: pool lease TTL (default 10)",
    )
    v.add_argument(
        "--devices", type=int, default=0, metavar="N",
        help="dispatch mode: every leased unit runs on a fleet sharded "
             "over N jax devices (shard x vmap; the mesh shape joins the "
             "unit's geometry bucket)",
    )
    v.add_argument(
        "--quota", default=None, metavar="RATE[:BURST]",
        help="per-tenant admission quota: token bucket of RATE "
             "submits/sec (burst default max(1,RATE)) per client id; "
             "rejected submits get retry_after_s backpressure",
    )
    v.add_argument(
        "--buckets", default="6x1,2x8", metavar="SxP[,SxP...]",
        help="capacity ladder: SLOTSxPAGES per bucket, one compiled fleet "
             "each, page = 64 event slots/core (default 6x1,2x8)",
    )
    v.add_argument("--chunk-steps", type=int, default=128)
    v.add_argument(
        "--max-queue", type=int, default=64, metavar="N",
        help="pending-queue bound; submits past it get RETRY_AFTER",
    )
    v.add_argument(
        "--checkpoint-wall", type=float, default=2.0, metavar="SEC",
        help="element-checkpoint in-flight jobs every SEC wall-seconds",
    )
    v.add_argument(
        "--idle-exit", type=float, default=None, metavar="SEC",
        help="exit 0 after SEC seconds with nothing queued or running "
             "(one-shot/CI mode; default: serve forever)",
    )
    v.add_argument(
        "--step-impl", choices=("xla", "pallas"), default=None,
        help="step implementation for the serving fleets",
    )
    v.add_argument(
        "--report", metavar="PATH",
        help="write a text report with the SERVICE section at drain",
    )
    v.add_argument(
        "--warm-cache", choices=("on", "off"), default="off",
        help="consult the on-disk warm-state cache at admission: a "
             "resubmitted (trace, config) job starts from the deepest "
             "matching cached state instead of step 0",
    )
    v.add_argument(
        "--replicas", default="", metavar="TARGET[,TARGET...]",
        help="replicate the journal to these follower daemons "
             "(`primetpu replica` targets, host:port or socket paths); "
             "'' (default) = replication off, bit-exact with today",
    )
    v.add_argument(
        "--quorum", type=int, default=None, metavar="K",
        help="replica ACKs required per frame (default: strict "
             "majority of the N replicas, N//2+1; any explicit K must "
             "satisfy 2K > N or quorums stop intersecting and fencing "
             "cannot be guaranteed)",
    )
    v.add_argument(
        "--quorum-policy", choices=("block", "degrade"), default="block",
        help="below quorum: block admission with ReplicaQuorumLost + "
             "retry_after_s (default), or degrade — keep ACKing on "
             "local fsync while flagging health/metrics",
    )
    v.add_argument(
        "--standby-of", default=None, metavar="TARGET",
        help="hot standby: tail --replicas while this primary target "
             "answers; once it stays dead past --takeover-grace, adopt "
             "the highest-epoch replica chain and promote (a fresh fencing "
             "epoch deposes the old primary)",
    )
    v.add_argument(
        "--takeover-grace", type=float, default=3.0, metavar="SEC",
        help="--standby-of: how long the primary must stay dead before "
             "promotion (default 3.0)",
    )
    _add_attest_flags(v)
    _add_fault_flags(v)
    _add_obs_flags(v)
    # no --overlap: the serving tick splices/retires slots between
    # chunks, so a speculated chunk would be invalidated every tick
    _add_exec_flags(v, overlap=False)
    v.set_defaults(fn=cmd_serve)

    rp = sub.add_parser(
        "replica",
        help="run one journal follower for replicated serving "
             "(DESIGN.md §21): byte-identical segment chain, fsynced "
             "before ACK, fencing-epoch aware",
    )
    rp.add_argument(
        "--dir", required=True, metavar="DIR",
        help="this follower's journal directory (its durability domain)",
    )
    rp.add_argument(
        "--socket", default=None, metavar="PATH",
        help="unix socket to listen on (default: DIR/replica.sock)",
    )
    rp.add_argument(
        "--tcp", default=None, metavar="HOST:PORT",
        help="listen on TCP instead (port 0 = kernel-assigned; the "
             "readiness line prints the real one)",
    )
    rp.set_defaults(fn=cmd_replica)

    b = sub.add_parser(
        "submit",
        help="submit one job to a running `primetpu serve` daemon",
    )
    b.add_argument("--socket", required=True, metavar="PATH|HOST:PORT",
                   help="daemon target: unix socket path or TCP host:port")
    b.add_argument("--trace", help="PTPU trace file (server-side path)")
    b.add_argument("--synth", help="synthetic workload spec name[:k=v,...]")
    b.add_argument(
        "--vary", action="append", metavar="K=V[,K=V...]",
        help="timing overrides for this job (same keys as sweep --vary)",
    )
    b.add_argument("--fold", action="store_true")
    b.add_argument(
        "--deadline", type=float, default=None, metavar="SEC",
        help="wall-clock budget from acceptance; expiry -> TIMEOUT",
    )
    b.add_argument("--max-steps", type=int, default=None)
    b.add_argument("--priority", type=int, default=0)
    b.add_argument("--client", default="anon")
    b.add_argument(
        "--retries", type=int, default=0, metavar="N",
        help="honor RETRY_AFTER backpressure up to N resubmits",
    )
    b.add_argument(
        "--wait", action="store_true",
        help="block until the job is terminal; exit 0 only on DONE",
    )
    b.add_argument("--timeout", type=float, default=300.0, metavar="SEC")
    b.set_defaults(fn=cmd_submit)

    t = sub.add_parser(
        "serve-status",
        help="healthz for a running daemon (queue depth, occupancy, "
             "aggregate MIPS, latency percentiles)",
    )
    t.add_argument("--socket", required=True, metavar="PATH|HOST:PORT",
                   help="daemon target: unix socket path or TCP host:port")
    t.add_argument(
        "--jobs", action="store_true", help="list every known job instead"
    )
    t.add_argument(
        "--drain", action="store_true",
        help="ask the daemon to finish its queue and exit",
    )
    t.add_argument(
        "--metrics", action="store_true",
        help="print the daemon's Prometheus text exposition (the same "
             "payload the `metrics` protocol verb serves)",
    )
    t.add_argument(
        "--watch", action="store_true",
        help="poll health and print one live status line per interval "
             "(queue, occupancy, MIPS, latency p50, last dispatch)",
    )
    t.add_argument(
        "--interval", type=float, default=2.0, metavar="SEC",
        help="--watch poll interval (default 2.0)",
    )
    t.add_argument(
        "--count", type=int, default=0, metavar="N",
        help="--watch: stop after N lines (default 0 = forever)",
    )
    t.set_defaults(fn=cmd_serve_status)

    li = sub.add_parser(
        "lint",
        help="check the source tree against the invariant catalog "
             "(DESIGN.md §19); exit 0 clean, 1 findings, 2 on analysis "
             "failure",
    )
    li.add_argument(
        "paths", nargs="*", metavar="PATH",
        help="files/dirs to lint (default: the primesim_tpu package)",
    )
    li.add_argument(
        "--root", default=None, metavar="DIR",
        help="repo root anchoring relative paths and the baseline "
             "(default: auto-detected from the installed package)",
    )
    li.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file of grandfathered findings "
             "(default: <root>/LINT_BASELINE.json)",
    )
    li.add_argument(
        "--select", action="append", metavar="RULE",
        help="run only this rule id (repeatable)",
    )
    li.add_argument(
        "--format", choices=("human", "json"), default="human",
    )
    li.set_defaults(fn=cmd_lint)

    fk = sub.add_parser(
        "fsck",
        help="statically verify durable artifacts (journals, ledgers, "
             "checkpoints, warm cache) under a directory; exit 2 with "
             "structured JSON on corruption",
    )
    fk.add_argument("dir", metavar="DIR", nargs="?",
                    help="artifact root to verify")
    fk.add_argument(
        "--compare", nargs=2, metavar=("DIR_A", "DIR_B"),
        help="instead of verifying one root, check two journal chains "
             "(primary vs replica) frame-for-frame up to the shorter "
             "one's durable point; divergence exits 2",
    )
    fk.add_argument(
        "--repair", choices=("none", "quarantine"), default="none",
        help="quarantine moves (never deletes) corrupt/orphaned files "
             "into DIR/.fsck-quarantine/",
    )
    fk.add_argument(
        "--format", choices=("human", "json"), default="human",
    )
    fk.set_defaults(fn=cmd_fsck)

    au = sub.add_parser(
        "audit",
        help="offline replay audit of a pool directory (DESIGN.md §24): "
             "re-execute DONE units from their journaled specs and "
             "compare fingerprint-chain heads against the ledger and "
             "the surviving checkpoints; exit 2 with structured JSON on "
             "divergence",
    )
    au.add_argument(
        "dir", metavar="DIR",
        help="pool directory (unit ledger + element checkpoints)",
    )
    au.add_argument(
        "--unit", action="append", metavar="ID",
        help="audit only this unit id (repeatable; default: every "
             "replayable unit)",
    )
    au.set_defaults(fn=cmd_audit)

    ch = sub.add_parser(
        "chaos",
        help="seeded crash campaign over the serve stack: generate "
             "fault plans, inject, machine-check durability invariants, "
             "shrink violations to a minimal repro artifact (DESIGN.md "
             "§20); exit 3 on violation",
    )
    ch.add_argument(
        "--trials", type=int, default=20,
        help="number of seeded trials (default 20)",
    )
    ch.add_argument(
        "--seed", type=int, default=0,
        help="first trial seed; trial k uses seed+k (default 0)",
    )
    ch.add_argument(
        "--classes", default="durable,crashpoint",
        help="comma list of fault classes to draw from: durable, "
             "crashpoint, socket, replication, silent_corruption, "
             "capacity_loss "
             "(default durable,crashpoint; replication runs the primary+"
             "replicas+standby failover trial and implies replica-kill "
             "crashpoints; silent_corruption flips committed counter "
             "bits on a pooled attested campaign and checks that no "
             "corrupted result reaches DONE unflagged; capacity_loss "
             "revokes devices from sharded supervised runs and opens "
             "sustained-ENOSPC windows, checking invariant G — no ACKed "
             "job lost, no bit-exactness violation; run it under "
             "XLA_FLAGS=--xla_force_host_platform_device_count=8 to "
             "give revocation a real mesh to shrink)",
    )
    ch.add_argument(
        "--max-events", type=int, default=3,
        help="max fault events per generated plan (default 3)",
    )
    ch.add_argument(
        "--out", default=None, metavar="DIR",
        help="write chaos-repro-<seed>.json artifacts here on violation",
    )
    ch.add_argument(
        "--plan", default=None, metavar="FILE",
        help="replay one plan/artifact JSON instead of generating "
             "(the repro loop)",
    )
    ch.add_argument(
        "--config", default=None,
        help="machine config JSON (default: small test config)",
    )
    ch.add_argument("--verbose", action="store_true",
                    help="per-trial progress on stderr")
    ch.set_defaults(fn=cmd_chaos)

    ca = sub.add_parser(
        "calibrate",
        help="fit traced timing knobs to a published microbenchmark "
             "latency/bandwidth table (DESIGN.md §25): coordinate-"
             "descent pattern search run as constant-shape fleets — "
             "one compile per geometry",
    )
    ca.add_argument("config", help="machine config JSON/XML")
    ca.add_argument(
        "--table", required=True, metavar="FILE",
        help="calibration table JSON (e.g. "
             "configs/calib_ipu_microbench.json)",
    )
    ca.add_argument(
        "--fit", default=None, metavar="K1,K2,...",
        help="comma list of knobs to fit (default cpi,l1_lat,llc_lat,"
             "link_lat,router_lat,dram_lat)",
    )
    ca.add_argument(
        "--rounds", type=int, default=24,
        help="max coordinate-descent rounds (default 24)",
    )
    ca.add_argument(
        "--chunk-steps", type=int, default=256,
        help="fleet chunk size in steps (default 256)",
    )
    ca.add_argument(
        "--out", default=None, metavar="FILE",
        help="write the full fit report (knobs, residuals, fitted "
             "config) as JSON",
    )
    ca.add_argument(
        "--selftest", action="store_true",
        help="replace the observed column with values simulated at "
             "ground-truth knobs and require the fit to recover them "
             "with ~zero residual (exit 1 otherwise)",
    )
    ca.add_argument(
        "--truth", default=None, metavar="K=V,...",
        help="selftest ground-truth knobs (default: a deterministic "
             "perturbation of the config's own values)",
    )
    ca.add_argument(
        "--tol", type=float, default=1e-6,
        help="selftest pass threshold on the summed squared relative "
             "residual (default 1e-6)",
    )
    ca.add_argument("--verbose", action="store_true",
                    help="per-coordinate-step progress on stderr")
    ca.set_defaults(fn=cmd_calibrate)
    return p


def main(argv=None) -> int:
    # subprocess chaos activation: a campaign exporting
    # PRIMETPU_CHAOS_PLAN makes every spawned worker/coordinator/server
    # inherit the fault plan (no-op when the var is unset)
    from ..chaos.sites import install_from_env

    install_from_env()
    ns = build_parser().parse_args(argv)
    from ..analysis.errors import AnalysisError, FsckCorrupt
    from ..attest.errors import AttestationError
    from ..calib.table import CalibError
    from ..config.machine import ConfigError, FaultConfigError
    from ..parallel.sharding import DeviceMeshError
    from ..sim.checkpoint import CheckpointCorrupt
    from ..trace.format import TraceError

    try:
        return ns.fn(ns)
    except (TraceError, ConfigError, FaultConfigError, CheckpointCorrupt,
            VarySpecError, AnalysisError, FsckCorrupt, DeviceMeshError,
            AttestationError, CalibError) as e:
        # typed errors exit 2 with ONE structured JSON line on stderr —
        # {"error": {type, location, detail}} — the same shape the serve
        # protocol and sweep quarantine lines use, so scripts parse one
        # grammar everywhere (location carries core/offset for traces,
        # site/step/field for fault schedules)
        from ..serve.protocol import error_obj

        print(json.dumps(error_obj(e)), file=sys.stderr)
        return 2
    except BrokenPipeError:  # e.g. `primetpu info cfg | head`
        return 0
