"""Golden reference simulator: slow, scalar, obviously correct.

Implements DESIGN.md's step semantics with plain Python/NumPy loops. This is
the oracle the vectorized JAX engine (`primesim_tpu/sim/engine.py`) must
match BIT-EXACTLY on per-core cycles, cache/directory state, and counters
(SURVEY.md §4: the single highest-value test asset the reference lacks).

Semantics map to the reference as: CoreManager per-core cycle accounting
(SURVEY.md §2 #2), Cache set-assoc lookup/LRU (#3), System directory-MESI
(#4), Network XY-hop latency (#6), Dram fixed latency (#7), and the relaxed
quantum barrier (#10) — all serialized here in the canonical deterministic
order DESIGN.md defines.
"""

from __future__ import annotations

import numpy as np

from ..config.machine import MachineConfig
from ..noc import topology as _topo
from ..noc.mesh import bank_tile, core_tile, n_links
from ..stats.counters import zero_counters
from ..trace.format import (
    EV_BARRIER,
    EV_END,
    EV_INS,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
    Trace,
)

# MESI encoding shared with the JAX engine; O (MOESI) is DERIVED — never
# stored in l1_state, only classified from the home directory's view
I, S, E, M, O = 0, 1, 2, 3, 4


class GoldenSim:
    def __init__(self, cfg: MachineConfig, trace: Trace):
        assert trace.n_cores == cfg.n_cores
        self.cfg = cfg
        self.trace = trace
        # internal addressing is LINE-granular (same normalization as the
        # engine: byte traces shift at ingest, v4 line traces pass through)
        self.events = trace.line_events(cfg.line_bits)
        C, B = cfg.n_cores, cfg.n_banks
        l1s, l1w = cfg.l1.sets, cfg.l1.ways
        ls, lw = cfg.llc.sets, cfg.llc.ways

        self.cycles = np.zeros(C, dtype=np.int64)
        self.ptr = np.zeros(C, dtype=np.int64)
        self.cpi = np.array(cfg.core.cpi_vector(C), dtype=np.int64)

        self.l1_tag = np.full((C, l1s, l1w), -1, dtype=np.int64)
        self.l1_state = np.full((C, l1s, l1w), I, dtype=np.int64)
        self.l1_lru = np.zeros((C, l1s, l1w), dtype=np.int64)

        self.llc_tag = np.full((B, ls, lw), -1, dtype=np.int64)
        self.llc_owner = np.full((B, ls, lw), -1, dtype=np.int64)
        self.llc_lru = np.zeros((B, ls, lw), dtype=np.int64)
        # sharer bit-vector words, matching the JAX engine's packed layout
        self.sharers = np.zeros((B, ls, lw, cfg.n_sharer_words), dtype=np.uint32)

        self.counters = zero_counters(C)
        self.quantum_end = cfg.quantum
        self.step_count = 0

        # hop-by-hop router model: per-directed-link next-free clock,
        # carried across steps (contention_model="router")
        self.link_free = np.zeros(n_links(cfg), dtype=np.int64)
        # memory-controller queueing (cfg.dram_queue): per-bank next-free
        # clock, carried across steps
        self.dram_free = np.zeros(B, dtype=np.int64)

        # stride-prefetcher training state (DESIGN.md §25; idle under
        # prefetcher "none" — mirrors MachineState.pf_*)
        self.pf_line = np.zeros(C, dtype=np.int64)
        self.pf_stride = np.zeros(C, dtype=np.int64)
        self.pf_streak = np.zeros(C, dtype=np.int64)

        # synchronization state (DESIGN.md §3 phase 2.7)
        self.lock_holder = np.full(cfg.lock_slots, -1, dtype=np.int64)
        self.barrier_count = np.zeros(cfg.barrier_slots, dtype=np.int64)
        self.barrier_time = np.zeros(cfg.barrier_slots, dtype=np.int64)
        self.sync_flag = np.zeros(C, dtype=np.int64)
        from ..trace.format import validate_sync

        validate_sync(trace, cfg.barrier_slots)

    # ------------------------------------------------------------ helpers

    def _bank(self, line: int) -> int:
        return line % self.cfg.n_banks

    def _bank_set(self, line: int) -> int:
        return (line // self.cfg.n_banks) % self.cfg.llc.sets

    def _l1_set(self, line: int) -> int:
        return line % self.cfg.l1.sets

    def _victim_way(self, tags, states, lrus):
        """Invalid-first LRU with lowest-index tie break (DESIGN.md §1)."""
        key = [(-1 if states[w] == I else int(lrus[w])) for w in range(len(tags))]
        return int(np.argmin(key))

    def _set_sharer(self, b, s, w, core, val: bool):
        # coarse vector (cfg.sharer_group > 1): the bit covers the whole
        # group of cores `core` belongs to
        g = core // self.cfg.sharer_group
        wi, bit = g // 32, g % 32
        if val:
            self.sharers[b, s, w, wi] |= np.uint32(1 << bit)
        else:
            self.sharers[b, s, w, wi] &= np.uint32(~(1 << bit) & 0xFFFFFFFF)

    def _clear_sharers(self, b, s, w):
        self.sharers[b, s, w, :] = 0

    def _derived_owned(self, c: int, line: int) -> bool:
        """MOESI derived-O test (DESIGN.md §25): core c's stored E/M line
        is effectively Owned when the home directory still names c owner
        WITH other sharers recorded (a GETS left the dirty copy in
        place). O is never stored — reads stay local, stores must
        arbitrate as upgrades to invalidate the sharers. Directory rows
        are unwritten between classification and phase 3, so the live
        read here equals the engine's step-start row."""
        if self.cfg.coherence != "moesi":
            return False
        b, bs = self._bank(line), self._bank_set(line)
        for wy in range(self.cfg.llc.ways):
            if self.llc_tag[b, bs, wy] == line:
                if self.llc_owner[b, bs, wy] != c:
                    return False
                shl = self._sharers_from(self.sharers, b, bs, wy)
                return any(t != c for t in shl)
        return False

    def _pf_hit(self, c: int, line: int) -> bool:
        """Stride-prefetch coverage test on core c's STEP-ENTRY training
        state (DESIGN.md §25): the line sits 1..prefetch_degree confirmed
        strides (streak >= 2) ahead of the last trained access. Safe to
        read live: only c's own winner/join trains c's state, and that
        happens after this test."""
        if self.cfg.prefetcher != "stride":
            return False
        s = int(self.pf_stride[c])
        if s == 0 or int(self.pf_streak[c]) < 2:
            return False
        delta = line - int(self.pf_line[c])
        q, rem = divmod(delta, s)  # floor semantics, same as the engine
        return rem == 0 and 1 <= q <= self.cfg.prefetch_degree

    def _pf_train(self, c: int, line: int) -> None:
        """Train the stride detector on a retired uncore access (winners
        + joins only — retries re-observe the same line and must not
        retrain; local L1 hits never reach the uncore)."""
        if self.cfg.prefetcher != "stride":
            return
        ns = line - int(self.pf_line[c])
        if ns == int(self.pf_stride[c]) and ns != 0:
            self.pf_streak[c] += 1
        else:
            self.pf_streak[c] = 1
        self.pf_stride[c] = ns
        self.pf_line[c] = line

    def _lock_slot(self, line: int) -> int:
        """Mutex LINE index -> lock-table slot (events are line-granular)."""
        return line & (self.cfg.lock_slots - 1)

    def _lock_home_tile(self, line: int) -> int:
        return bank_tile(self._bank(line), self.cfg)

    # topology dispatch (DESIGN.md §25): every hop count, one-way latency
    # and route in the golden model goes through noc/topology.py, so the
    # torus/ring plugins are oracle-checked by the same parity suite
    def _thops(self, tile_a: int, tile_b: int) -> int:
        return int(_topo.hops(self.cfg, tile_a, tile_b, xp=np))

    def _owl(self, tile_a: int, tile_b: int) -> int:
        return int(_topo.one_way_lat(self.cfg, tile_a, tile_b))

    def _links(self, tile_a: int, tile_b: int) -> list[int]:
        return list(_topo.route_links(self.cfg, tile_a, tile_b))

    def _noc(self, c: int, tile_a: int, tile_b: int):
        """Charge one message tile_a->tile_b to core c's NoC counters."""
        lat = self._owl(tile_a, tile_b)
        self.counters["noc_msgs"][c] += 1
        self.counters["noc_hops"][c] += self._thops(tile_a, tile_b)
        return lat

    def _txn_path(self, ctile: int, htile: int, round_trip: bool) -> list[int]:
        p = self._links(ctile, htile)
        if round_trip:
            p = p + self._links(htile, ctile)
        return p

    def _contention_extra(
        self, c: int, ctile: int, htile: int, round_trip: bool = True
    ) -> int:
        """Queueing charge for core c's transaction from `ctile` to home
        `htile` this step (0 when the model is disabled). Tile model:
        occupancy at the home tile; link model: bottleneck occupancy over
        the transaction's XY path links. The router model charges through
        `_route` instead (this returns 0 so analytic compositions stay
        clean and the router surcharge replaces them wholesale)."""
        cfg = self.cfg
        if not cfg.noc.contention:
            return 0
        if cfg.noc.contention_model == "router":
            return 0
        if cfg.noc.contention_model == "tile":
            extra = cfg.noc.contention_lat * (self._tile_txns.get(htile, 1) - 1)
        else:
            worst = 0
            for l in self._txn_path(ctile, htile, round_trip):
                worst = max(worst, self._link_cnt.get(l, 1) - 1)
            extra = cfg.noc.contention_lat * worst
        self.counters["noc_contention_cycles"][c] += extra
        return extra

    # ------------------------------------------ hop-by-hop router model

    @property
    def _router_on(self) -> bool:
        return (
            self.cfg.noc.contention
            and self.cfg.noc.contention_model == "router"
        )

    def _rtr_rank(self, link: int, key) -> int:
        """FIFO position among this step's packets on `link`: how many
        same-step transactions with a smaller (clock, core) key also
        traverse it. Fixed at step entry — every transaction's charge
        depends only on carried link clocks, the step's fixed rank/anchor
        tables, and its own timings, which is what makes the vectorized
        engine bit-exact."""
        return sum(1 for k in self._rtr_users.get(link, ()) if k < key)

    def _route(self, t0: int, path, key) -> int:
        """Walk one packet over `path` hop by hop against the carried
        per-link clocks: at each link wait for
        `max(link_free, base) + rank*link_lat` — `base` is the link's
        EARLIEST NOMINAL (uncontended) arrival among this step's packets,
        so same-step FIFO serialization anchors at when the link's queue
        actually starts forming, not at a long-idle link clock — then
        occupy the link for link_lat and pay router_lat at the next
        router; waits cascade into later hops. Records each departure for
        the end-of-step clock advance. Returns the arrival time;
        uncontended this is exactly t0 + hops*link_lat +
        (hops+1)*router_lat (the analytic one-way)."""
        noc = self.cfg.noc
        t = t0 + noc.router_lat
        for l in path:
            rank = self._rtr_rank(l, key)
            anchor = max(int(self.link_free[l]), self._rtr_base.get(l, 0))
            t = max(t, anchor + rank * noc.link_lat)
            self._rtr_departs.append((l, t + noc.link_lat))
            t += noc.link_lat + noc.router_lat
        return t

    def _route_rt(self, c: int, t0: int, htile: int, service: int) -> int:
        """Round-trip request->service->reply through the router, keyed
        by core c's recorded step-entry key. Returns completion time."""
        ctile = core_tile(c, self.cfg)
        key = self._rtr_key[c]
        t = self._route(t0, self._links(ctile, htile), key)
        return self._route(t + service, self._links(htile, ctile), key)

    def _rtr_end(self) -> None:
        for l, d in self._rtr_departs:
            if d > self.link_free[l]:
                self.link_free[l] = d
        self._rtr_departs = []

    # --------------------------------------------------------------- step

    def done(self) -> bool:
        t = self.events
        return all(
            t[c, min(int(self.ptr[c]), self.trace.max_len - 1), 0] == EV_END
            for c in range(self.cfg.n_cores)
        )

    def step(self) -> None:
        cfg = self.cfg
        C = cfg.n_cores
        ev = self.events

        # --- quantum barrier (DESIGN.md §3): bump quantum_end if nobody
        # active. Barrier-frozen cores neither bump nor bound the quantum.
        cur = [ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)] for c in range(C)]
        not_done = [c for c in range(C) if cur[c][0] != EV_END]
        if not not_done:
            return

        def _frozen(c):
            return cur[c][0] == EV_BARRIER and self.sync_flag[c]

        countable = [c for c in not_done if not _frozen(c)]
        active = [c for c in countable if self.cycles[c] < self.quantum_end]
        if not active and countable:
            m = min(int(self.cycles[c]) for c in countable)
            self.quantum_end = (m // cfg.quantum + 1) * cfg.quantum
            active = [c for c in countable if self.cycles[c] < self.quantum_end]
        # Clock-window invariant (DESIGN.md §3-sync): every active core's
        # clock lies in [quantum_end - Q, quantum_end). The JAX engine's
        # packed arbitration keys (rel*C + core) REQUIRE this; asserting it
        # here makes every golden/parity test also an invariant check.
        assert all(
            self.cycles[c] >= self.quantum_end - cfg.quantum for c in active
        ), "clock-window invariant violated"

        step = self.step_count
        self.step_count += 1

        # --- phase 0.5: local runs (DESIGN.md §3) --------------------------
        # Each active core first retires up to `local_run_len` LOCAL events
        # (INS batches, L1 read hits, L1 write hits in E/M) in order, judged
        # against the live directory (which no run modifies — runs touch only
        # the core's own L1 row: LRU refresh, silent E->M) and the core's own
        # live L1 state. The run stops at the first non-local event, at the
        # quantum boundary, or after local_run_len events. The event then at
        # ptr enters the normal per-step phases below.
        for c in active:
            for _ in range(cfg.local_run_len):
                if self.cycles[c] >= self.quantum_end:
                    break
                e = ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
                t, arg, addr = int(e[0]), int(e[1]), int(e[2])
                pre = int(e[3])
                if t == EV_END:
                    break
                if t == EV_INS:
                    self.cycles[c] += arg * int(self.cpi[c])
                    self.counters["instructions"][c] += arg
                    self.ptr[c] += 1
                    continue
                if t not in (EV_LD, EV_ST):
                    break  # sync events are never local: arbitrate below
                line = addr  # line-granular events
                s = self._l1_set(line)
                w = -1
                for wy in range(cfg.l1.ways):
                    if (
                        self.l1_tag[c, s, wy] == line
                        and self.l1_state[c, s, wy] != I
                    ):
                        w = wy
                        break
                if w < 0:
                    break  # miss: stop the run, arbitrate below
                if t == EV_ST and (
                    self.l1_state[c, s, w] not in (E, M)
                    or self._derived_owned(c, line)
                ):
                    break  # held in S (or derived O): upgrade, arbitrate
                self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                self.counters["instructions"][c] += pre + 1
                if t == EV_LD:
                    self.counters["l1_read_hits"][c] += 1
                else:
                    self.counters["l1_write_hits"][c] += 1
                    self.l1_state[c, s, w] = M  # silent E->M
                self.l1_lru[c, s, w] = step
                self.ptr[c] += 1
        if cfg.local_run_len:
            # re-gather events and the active set at the post-run pointers
            cur = [
                ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
                for c in range(C)
            ]
            active = [
                c
                for c in range(C)
                if cur[c][0] != EV_END
                and not _frozen(c)
                and self.cycles[c] < self.quantum_end
            ]

        # --- phase 0/1: classify against step-start state ------------------
        # Only the L1 tag/state arrays need step-start snapshots: phase-3
        # reads of OTHER cores' L1 rows (owner probes) must not see this
        # step's phase-A writes. Every other read in the step touches rows
        # that nothing else writes within the step (winners own their
        # (bank,set) exclusively; cores own their L1 row), so live arrays
        # are equivalent and the expensive LLC copies are skipped.
        l1_tag0 = self.l1_tag.copy()
        l1_state0 = self.l1_state.copy()

        # request tuple: (cycles, core, kind, line, pre)
        requests = []
        joins = []  # read-join candidates: (core, line, pre)
        lock_reqs = []  # (cycles, core, addr, pre)
        unlocks = []  # (core, addr, pre)
        barrier_arr = []  # (core, barrier id, n participants, pre)
        GETS, GETM, UPG = 0, 1, 2

        for c in active:
            t, arg, addr = int(cur[c][0]), int(cur[c][1]), int(cur[c][2])
            pre = int(cur[c][3])  # pre-batched non-memory instructions
            if t == EV_INS:
                self.cycles[c] += arg * int(self.cpi[c])
                self.counters["instructions"][c] += arg
                self.ptr[c] += 1
                continue
            if t == EV_LOCK:
                lock_reqs.append((int(self.cycles[c]), c, addr, pre))
                continue
            if t == EV_UNLOCK:
                unlocks.append((c, addr, pre))
                continue
            if t == EV_BARRIER:
                barrier_arr.append((c, addr, arg, pre))
                continue
            line = addr  # line-granular events
            s = self._l1_set(line)
            w = -1
            for wy in range(cfg.l1.ways):
                if l1_tag0[c, s, wy] == line and l1_state0[c, s, wy] != I:
                    w = wy
                    break
            if t == EV_LD:
                if w >= 0:  # read hit
                    self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                    self.counters["l1_read_hits"][c] += 1
                    self.counters["instructions"][c] += pre + 1
                    self.l1_lru[c, s, w] = step  # phase A local
                    self.ptr[c] += 1
                elif self._join_eligible(c, line):
                    joins.append((c, line, pre))
                else:
                    requests.append((int(self.cycles[c]), c, GETS, line, pre))
            else:  # EV_ST
                if (
                    w >= 0
                    and l1_state0[c, s, w] in (E, M)
                    and not self._derived_owned(c, line)
                ):  # write hit (E/M exactly — derived O must arbitrate)
                    self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                    self.counters["l1_write_hits"][c] += 1
                    self.counters["instructions"][c] += pre + 1
                    self.l1_state[c, s, w] = M  # silent E->M, phase A local
                    self.l1_lru[c, s, w] = step
                    self.ptr[c] += 1
                elif w >= 0:  # held in S (or derived O) -> upgrade
                    requests.append((int(self.cycles[c]), c, UPG, line, pre))
                else:
                    requests.append((int(self.cycles[c]), c, GETM, line, pre))

        # --- phase 2: per-(bank,set) conflict serialization ----------------
        # Read-joins (GETS to a shared, ownerless, already-shared line)
        # coalesce: any number retire in one step, bit-exact to any
        # serialization order because the join path's latency is independent
        # of the sharer set and the sharer-bit updates commute (DESIGN.md
        # §3). A join only proceeds if no arbitrating request targets its
        # home (bank,set) this step; otherwise it demotes to a normal GETS.
        arb_slots = {
            (self._bank(r[3]), self._bank_set(r[3])) for r in requests
        }
        join_go = []
        for c, line, pre in joins:
            if (self._bank(line), self._bank_set(line)) in arb_slots:
                requests.append((int(self.cycles[c]), c, GETS, line, pre))
            else:
                join_go.append((c, line, pre))

        by_bankset: dict[tuple[int, int], list] = {}
        for r in requests:
            key = (self._bank(r[3]), self._bank_set(r[3]))
            by_bankset.setdefault(key, []).append(r)
        winners = []
        for key, rs in by_bankset.items():
            rs.sort(key=lambda r: (r[0], r[1]))  # (cycles, core_id)
            winners.append(rs[0])
            for r in rs[1:]:
                self.counters["retries"][r[1]] += 1

        # --- contention occupancy counts (NocConfig.contention) -----------
        # Tile model: every uncore transaction served at a home tile this
        # step queues behind the others there. Link model: every directed
        # mesh link on a transaction's XY request+reply path (barrier
        # arrivals: one-way) is claimed by it. Counts are fixed BEFORE any
        # charging so the extra is identical for every transaction sharing
        # a tile/link (matching the engine's one-scatter count). The
        # transaction classes: memory winners + joins (home bank),
        # lock/unlock RMWs (lock home), barrier arrivals (barrier home).
        self._tile_txns = {}
        self._link_cnt = {}
        self._rtr_users = {}
        self._rtr_base = {}
        self._rtr_key = {}
        self._rtr_departs = []
        if cfg.noc.contention:
            link_model = cfg.noc.contention_model == "link"
            router = cfg.noc.contention_model == "router"
            c_hop = cfg.noc.link_lat + cfg.noc.router_lat
            r_lat = cfg.noc.router_lat

            def _bump(c, htile, round_trip=True, key=None, t0=0):
                if router:
                    # record this packet's links, canonical key, and
                    # NOMINAL (uncontended) per-link arrival times; ranks
                    # and queue anchors are computed against this fixed
                    # set. Reply-leg nominals assume llc.latency service
                    # (the model's defined anchor — the real service may
                    # be longer; `base` is a min, so early is safe).
                    self._rtr_key[c] = key
                    ctile = core_tile(c, cfg)
                    req = self._links(ctile, htile)
                    legs = [(req, t0)]
                    if round_trip:
                        legs.append(
                            (
                                self._links(htile, ctile),
                                t0
                                + r_lat
                                + len(req) * c_hop
                                + cfg.llc.latency,
                            )
                        )
                    seen = set()
                    for path, leg_t0 in legs:
                        for k, l in enumerate(path):
                            a = leg_t0 + r_lat + k * c_hop
                            if (b := self._rtr_base.get(l)) is None or a < b:
                                self._rtr_base[l] = a
                            if l not in seen:
                                seen.add(l)
                                self._rtr_users.setdefault(l, []).append(key)
                elif link_model:
                    ctile = core_tile(c, cfg)
                    for l in self._txn_path(ctile, htile, round_trip):
                        self._link_cnt[l] = self._link_cnt.get(l, 0) + 1
                else:
                    self._tile_txns[htile] = self._tile_txns.get(htile, 0) + 1

            l1lat = cfg.l1.latency
            for cyc, c, _, line, pre in winners:
                _bump(
                    c,
                    bank_tile(self._bank(line), cfg),
                    key=(cyc, c),
                    t0=cyc + pre * int(self.cpi[c]) + l1lat,
                )
            for c, line, pre in join_go:
                cy = int(self.cycles[c])
                _bump(
                    c,
                    bank_tile(self._bank(line), cfg),
                    key=(cy, c),
                    t0=cy + pre * int(self.cpi[c]) + l1lat,
                )
            for c, addr, pre in unlocks:
                cy = int(self.cycles[c])
                _bump(
                    c,
                    self._lock_home_tile(addr),
                    key=(cy, c),
                    t0=cy + pre * int(self.cpi[c]),
                )
            for cyc, c, addr, pre in lock_reqs:
                first = self.sync_flag[c] == 0
                _bump(
                    c,
                    self._lock_home_tile(addr),
                    key=(cyc, c),
                    t0=cyc + (pre * int(self.cpi[c]) if first else 0),
                )
            for c, bid, _, pre in barrier_arr:
                cy = int(self.cycles[c])
                _bump(
                    c,
                    bid % cfg.n_tiles,
                    round_trip=False,
                    key=(cy, c),
                    t0=cy + pre * int(self.cpi[c]),
                )

        for c, line, pre in join_go:
            self._do_join(c, line, pre, step)

        # --- memory-controller queue pre-pass (cfg.dram_queue) -------------
        # This step's DRAM transactions (miss winners) and their NOMINAL
        # controller arrivals are fixed BEFORE any winner is processed, so
        # ranks/anchors are step-scoped exactly like the router model's;
        # the per-slot uniqueness of winners makes the hit peek identical
        # to the processing-time lookup.
        self._dram_users = {}
        self._dram_base = {}
        self._dram_arr = {}
        self._dram_starts = []
        if cfg.dram_queue:
            svc = cfg.dram_service or cfg.dram_lat
            for cyc, c, kind, line, pre in winners:
                b, bs = self._bank(line), self._bank_set(line)
                if any(
                    self.llc_tag[b, bs, w] == line
                    for w in range(cfg.llc.ways)
                ):
                    continue  # LLC hit: no controller access
                if self._pf_hit(c, line):
                    continue  # prefetch-covered miss: no controller access
                a = (
                    cyc
                    + pre * int(self.cpi[c])
                    + cfg.l1.latency
                    + self._owl(core_tile(c, cfg), bank_tile(b, cfg))
                    + cfg.llc.latency
                )
                self._dram_users.setdefault(b, []).append((cyc, c))
                self._dram_arr[c] = a
                if b not in self._dram_base or a < self._dram_base[b]:
                    self._dram_base[b] = a

        # --- phase 3: transitions on step-start state; collect phase-B ops -
        # Phase-B op = (core, line, op) with op in {"downgrade","invalidate"}
        phase_b: list[tuple[int, int, str]] = []

        for cyc, c, kind, line, pre in sorted(winners, key=lambda r: r[1]):
            b = self._bank(line)
            bs = self._bank_set(line)
            ctile = core_tile(c, cfg)
            btile = bank_tile(b, cfg)

            lat = cfg.l1.latency
            lat += self._noc(c, ctile, btile)  # request
            lat += cfg.llc.latency

            # LLC lookup (step-start)
            hitw = -1
            for wy in range(cfg.llc.ways):
                if self.llc_tag[b, bs, wy] == line:
                    hitw = wy
                    break

            if kind == GETS:
                self.counters["l1_read_misses"][c] += 1
            elif kind == GETM:
                self.counters["l1_write_misses"][c] += 1
            else:
                self.counters["upgrades"][c] += 1

            if hitw >= 0:
                self.counters["llc_hits"][c] += 1
                w = hitw
                owner = int(self.llc_owner[b, bs, w])
                recorded = self._sharers_from(self.sharers, b, bs, w)
                shl = [t for t in recorded if t != c]
                # coarse vector: "shared" means ANY group bit is set —
                # the requester's own group bit may cover other cores, so
                # exclusivity requires an empty vector
                shared_any = (
                    bool(shl)
                    if cfg.sharer_group == 1
                    else self._any_sharer_bit(b, bs, w)
                )
                if kind == GETS:
                    if owner >= 0 and owner != c:
                        # probe owner (charged regardless of staleness)
                        otile = core_tile(owner, cfg)
                        lat += self._noc(c, btile, otile)
                        lat += self._noc(c, otile, btile)
                        self.counters["probes"][c] += 1
                        if cfg.coherence == "moesi":
                            # dirty sharing: the probed owner KEEPS the
                            # line (derives to O on its next access) and
                            # existing sharers stay recorded — no
                            # downgrade op, no owner clear
                            pass
                        else:
                            phase_b.append((owner, line, "downgrade"))
                            self.llc_owner[b, bs, w] = -1
                            self._clear_sharers(b, bs, w)
                        self._set_sharer(b, bs, w, c, True)
                        # The directory cannot observe silent L1 evictions,
                        # so the probed owner is conservatively re-recorded
                        # as a sharer whether or not it still holds the line
                        # (recorded sharers stay a superset of holders) —
                        # exactly what a real home node does, and it keeps
                        # the home-side transition free of any read of the
                        # owner's private cache state.
                        self._set_sharer(b, bs, w, owner, True)
                        grant = S
                    elif shared_any:
                        # no-op under mesi (owner >= 0 implies an empty
                        # sharer vector there); under moesi the owner's
                        # OWN refetch after a silent eviction lands here
                        # and relinquishes ownership
                        self.llc_owner[b, bs, w] = -1
                        self._set_sharer(b, bs, w, c, True)
                        grant = S
                    else:
                        self.llc_owner[b, bs, w] = c
                        self._clear_sharers(b, bs, w)
                        grant = E
                else:  # GETM or UPG
                    inv_lat = 0
                    if owner >= 0 and owner != c:
                        otile = core_tile(owner, cfg)
                        lat += self._noc(c, btile, otile)
                        lat += self._noc(c, otile, btile)
                        self.counters["probes"][c] += 1
                        phase_b.append((owner, line, "invalidate"))
                    # serialization latency spans every RECORDED core of
                    # flagged groups (coarse mode: including the
                    # requester's own slot — the home node serializes the
                    # whole group broadcast); messages/counters/phase-B
                    # go to the recorded cores minus the requester
                    for tcore in recorded:
                        ttile = core_tile(tcore, cfg)
                        rt = self._owl(btile, ttile) * 2
                        if cfg.sharer_group > 1 or tcore != c:
                            inv_lat = max(inv_lat, rt)
                    for tcore in shl:
                        ttile = core_tile(tcore, cfg)
                        self.counters["invalidations"][c] += 1
                        self.counters["noc_msgs"][c] += 2
                        self.counters["noc_hops"][c] += 2 * self._thops(
                            btile, ttile
                        )
                        phase_b.append((tcore, line, "invalidate"))
                    lat += inv_lat
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = M
                self.llc_lru[b, bs, w] = step
            else:
                # LLC miss -> DRAM + fill (UPG stale corner handled as GETM)
                self.counters["llc_misses"][c] += 1
                self.counters["dram_accesses"][c] += 1
                self.counters["noc_msgs"][c] += 2  # to co-located controller
                if self._pf_hit(c, line):
                    # covered by the stride prefetcher: pay the buffer
                    # latency, skip the controller queue AND dram_lat
                    # (dram_accesses above still counts it — the fetch
                    # happened, just earlier)
                    self.counters["prefetch_hits"][c] += 1
                    lat += cfg.prefetch_lat
                else:
                    if cfg.dram_queue:
                        svc = cfg.dram_service or cfg.dram_lat
                        bkey = (cyc, c)
                        rank = sum(
                            1 for k in self._dram_users.get(b, ()) if k < bkey
                        )
                        a = self._dram_arr[c]
                        start = max(
                            a,
                            max(int(self.dram_free[b]), self._dram_base[b])
                            + rank * svc,
                        )
                        self.counters["dram_queue_cycles"][c] += start - a
                        lat += start - a
                        self._dram_starts.append((b, start + svc))
                    lat += cfg.dram_lat
                # victim selection on step-start state
                w = self._victim_way(
                    self.llc_tag[b, bs],
                    self._llc_valid(self.llc_tag, b, bs),
                    self.llc_lru[b, bs],
                )
                if self.llc_tag[b, bs, w] != -1:
                    vline = int(self.llc_tag[b, bs, w])
                    vowner = int(self.llc_owner[b, bs, w])
                    vtargets = self._sharers_from(self.sharers, b, bs, w)
                    if vowner >= 0:
                        self.counters["llc_writebacks"][c] += 1
                        if vowner not in vtargets:
                            vtargets = vtargets + [vowner]
                    for tcore in vtargets:
                        ttile = core_tile(tcore, cfg)
                        self.counters["invalidations"][c] += 1
                        self.counters["noc_msgs"][c] += 2
                        self.counters["noc_hops"][c] += 2 * self._thops(
                            btile, ttile
                        )
                        phase_b.append((tcore, vline, "invalidate"))
                self.llc_tag[b, bs, w] = line
                self.llc_lru[b, bs, w] = step
                if kind == GETS:
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = E
                else:
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = M

            lat += self._noc(c, btile, ctile)  # reply
            lat += self._contention_extra(c, ctile, btile)

            if self._router_on:
                # replace the analytic request/reply legs with the hop-by
                # -hop walk; everything between them (LLC, probes,
                # invalidations, DRAM) is the service interval
                req_a = self._owl(ctile, btile)
                rep_a = self._owl(btile, ctile)
                service = lat - cfg.l1.latency - req_a - rep_a
                t0 = cyc + pre * int(self.cpi[c]) + cfg.l1.latency
                t_end = self._route_rt(c, t0, btile, service)
                raw = cfg.l1.latency + (t_end - t0)
                self.counters["noc_contention_cycles"][c] += raw - lat
                lat = raw

            # O3-style overlap: hide a fraction of the miss latency
            ov = cfg.core.o3_overlap_256
            if ov:
                lat = lat - ((lat * ov) >> 8)

            # --- phase 4.A for this winner: L1 update ----------------------
            s = self._l1_set(line)
            curw = -1
            for wy in range(cfg.l1.ways):
                if l1_tag0[c, s, wy] == line and l1_state0[c, s, wy] != I:
                    curw = wy
                    break
            if kind == UPG and curw >= 0:
                self.l1_state[c, s, curw] = grant
                self.l1_lru[c, s, curw] = step
            else:
                vw = self._victim_way(
                    l1_tag0[c, s],
                    l1_state0[c, s],
                    self.l1_lru[c, s],
                )
                if l1_state0[c, s, vw] == M:
                    self.counters["l1_writebacks"][c] += 1
                self.l1_tag[c, s, vw] = line
                self.l1_state[c, s, vw] = grant
                self.l1_lru[c, s, vw] = step

            self.cycles[c] += pre * int(self.cpi[c]) + lat
            self.counters["instructions"][c] += pre + 1
            self.ptr[c] += 1
            self._pf_train(c, line)

        # --- phase 4.B: remote ops, tag-conditional against live state -----
        for tcore, line, op in phase_b:
            s = self._l1_set(line)
            for wy in range(cfg.l1.ways):
                if self.l1_tag[tcore, s, wy] == line and self.l1_state[tcore, s, wy] != I:
                    if op == "downgrade":
                        if self.l1_state[tcore, s, wy] in (E, M):
                            self.l1_state[tcore, s, wy] = S
                    else:
                        self.l1_state[tcore, s, wy] = I
                    break

        # --- phase 2.7: synchronization events (DESIGN.md) -----------------
        # Sync and memory phases touch disjoint per-core/table state, so
        # their relative order within the step is immaterial; unlocks ->
        # lock grants -> barrier arrivals -> releases is the canonical
        # order WITHIN sync.
        for c, addr, pre in unlocks:
            s = self._lock_slot(addr)
            h = self._lock_home_tile(addr)
            ctile = core_tile(c, cfg)
            lat = self._noc(c, ctile, h) + cfg.llc.latency + self._noc(c, h, ctile)
            lat += self._contention_extra(c, ctile, h)
            if self._router_on:
                t0 = int(self.cycles[c]) + pre * int(self.cpi[c])
                t_end = self._route_rt(c, t0, h, cfg.llc.latency)
                self.counters["noc_contention_cycles"][c] += (t_end - t0) - lat
                lat = t_end - t0
            self.cycles[c] += pre * int(self.cpi[c]) + lat
            self.counters["instructions"][c] += pre + 1
            if self.lock_holder[s] == c:
                self.lock_holder[s] = -1
            self.ptr[c] += 1

        by_slot: dict[int, list] = {}
        for r in lock_reqs:
            by_slot.setdefault(self._lock_slot(r[2]), []).append(r)
        for s, rs in sorted(by_slot.items()):
            rs.sort(key=lambda r: (r[0], r[1]))  # (cycles, core_id)
            for i, (cyc, c, addr, pre) in enumerate(rs):
                h = self._lock_home_tile(addr)
                ctile = core_tile(c, cfg)
                # every attempt (grant or spin) is a charged RMW round trip
                lat = (
                    self._noc(c, ctile, h)
                    + cfg.llc.latency
                    + self._noc(c, h, ctile)
                )
                lat += self._contention_extra(c, ctile, h)
                if self._router_on:
                    t0 = int(self.cycles[c]) + (
                        pre * int(self.cpi[c]) if self.sync_flag[c] == 0 else 0
                    )
                    t_end = self._route_rt(c, t0, h, cfg.llc.latency)
                    self.counters["noc_contention_cycles"][c] += (
                        t_end - t0
                    ) - lat
                    lat = t_end - t0
                if self.sync_flag[c] == 0:  # first attempt: charge pre batch
                    self.cycles[c] += pre * int(self.cpi[c])
                    self.counters["instructions"][c] += pre
                self.cycles[c] += lat
                holder = int(self.lock_holder[s])
                if holder == c or (i == 0 and holder == -1):
                    self.lock_holder[s] = c
                    self.counters["lock_acquires"][c] += 1
                    self.counters["instructions"][c] += 1
                    self.sync_flag[c] = 0
                    self.ptr[c] += 1
                else:
                    self.counters["lock_spins"][c] += 1
                    self.sync_flag[c] = 1

        for c, bid, n, pre in barrier_arr:
            h = bid % cfg.n_tiles
            ctile = core_tile(c, cfg)
            self.cycles[c] += pre * int(self.cpi[c])
            self.counters["instructions"][c] += pre
            arr_lat = self._noc(c, ctile, h)  # arrival message
            if self._router_on:
                t0 = int(self.cycles[c])
                t_end = self._route(
                    t0,
                    self._links(ctile, h),
                    self._rtr_key[c],
                )
                self.counters["noc_contention_cycles"][c] += (
                    t_end - t0
                ) - arr_lat
                arr_lat = t_end - t0
            self.cycles[c] += arr_lat
            self.cycles[c] += self._contention_extra(c, ctile, h, round_trip=False)
            self.counters["barrier_waits"][c] += 1
            self.sync_flag[c] = 1
            self.barrier_count[bid] += 1
            self.barrier_time[bid] = max(
                int(self.barrier_time[bid]), int(self.cycles[c])
            )

        # releases: every waiter whose slot count reached ITS participant
        # count resumes at the slot's max arrival time + wake-up message
        waiting: dict[int, list] = {}
        for c in range(C):
            e = ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
            if int(e[0]) == EV_BARRIER and self.sync_flag[c]:
                waiting.setdefault(int(e[2]), []).append((c, int(e[1])))
        for bid, ws in sorted(waiting.items()):
            rel = [c for c, n in ws if self.barrier_count[bid] >= n]
            for c in rel:
                h = bid % cfg.n_tiles
                ctile = core_tile(c, cfg)
                self.cycles[c] = int(self.barrier_time[bid]) + self._noc(
                    c, h, ctile
                )
                self.counters["instructions"][c] += 1
                self.sync_flag[c] = 0
                self.ptr[c] += 1
            self.barrier_count[bid] -= len(rel)
            if self.barrier_count[bid] <= 0:
                self.barrier_count[bid] = 0
                self.barrier_time[bid] = 0

        # hop-by-hop router: advance each touched link's clock to its
        # last departure (deferred to step end so every transaction
        # charged this step saw the same carried link state)
        if self._router_on:
            self._rtr_end()
        for b, d in self._dram_starts:
            if d > self.dram_free[b]:
                self.dram_free[b] = d

    # ------------------------------------------------------ read-join path

    def _join_eligible(self, c: int, line: int) -> bool:
        """GETS may coalesce iff the line is LLC-resident, ownerless, and
        already shared by someone else (DESIGN.md §3 'plain join' case —
        the only transition whose outcome and latency are independent of
        concurrent same-line readers). Disabled under the coarse sharer
        vector: two same-group joiners' bit updates would collide in the
        engine's single fused scatter-add (and coarse 'shared' cannot
        distinguish self-only anyway)."""
        if self.cfg.sharer_group > 1:
            return False
        b, bs = self._bank(line), self._bank_set(line)
        for wy in range(self.cfg.llc.ways):
            if self.llc_tag[b, bs, wy] == line:
                if self.llc_owner[b, bs, wy] >= 0:
                    return False
                shl = self._sharers_from(self.sharers, b, bs, wy)
                return any(t != c for t in shl)
        return False

    def _do_join(self, c: int, line: int, pre: int, step: int) -> None:
        """Retire one coalesced read-join (same outcome as the serialized
        'sharers non-empty -> S, sharers |= {c}' path)."""
        cfg = self.cfg
        b, bs = self._bank(line), self._bank_set(line)
        ctile, btile = core_tile(c, cfg), bank_tile(b, cfg)
        w = -1
        for wy in range(cfg.llc.ways):
            if self.llc_tag[b, bs, wy] == line:
                w = wy
                break
        self.counters["l1_read_misses"][c] += 1
        self.counters["llc_hits"][c] += 1
        lat = cfg.l1.latency
        lat += self._noc(c, ctile, btile)
        lat += cfg.llc.latency
        self._set_sharer(b, bs, w, c, True)
        self.llc_lru[b, bs, w] = step
        lat += self._noc(c, btile, ctile)
        lat += self._contention_extra(c, ctile, btile)
        if self._router_on:
            req_a = self._owl(ctile, btile)
            rep_a = self._owl(btile, ctile)
            service = lat - cfg.l1.latency - req_a - rep_a  # llc.latency
            t0 = int(self.cycles[c]) + pre * int(self.cpi[c]) + cfg.l1.latency
            t_end = self._route_rt(c, t0, btile, service)
            raw = cfg.l1.latency + (t_end - t0)
            self.counters["noc_contention_cycles"][c] += raw - lat
            lat = raw
        ov = cfg.core.o3_overlap_256
        if ov:
            lat = lat - ((lat * ov) >> 8)
        # L1 fill (victim on step-start state == live state for this set:
        # joins are this core's only action this step)
        s = self._l1_set(line)
        vw = self._victim_way(
            self.l1_tag[c, s], self.l1_state[c, s], self.l1_lru[c, s]
        )
        if self.l1_state[c, s, vw] == M:
            self.counters["l1_writebacks"][c] += 1
        self.l1_tag[c, s, vw] = line
        self.l1_state[c, s, vw] = S
        self.l1_lru[c, s, vw] = step
        self.cycles[c] += pre * int(self.cpi[c]) + lat
        self.counters["instructions"][c] += pre + 1
        self.ptr[c] += 1
        self._pf_train(c, line)

    # ----------------------------------------------------- static helpers

    def _llc_valid(self, llc_tag0, b, bs):
        """Map tags to pseudo-states for victim selection (valid=1, I=0)."""
        return [I if llc_tag0[b, bs, w] == -1 else S for w in range(self.cfg.llc.ways)]

    def _sharers_from(self, sharers0, b, s, w) -> list[int]:
        """RECORDED sharer cores of an entry: with the full-map vector,
        exactly the cores whose bits are set; with a coarse vector
        (sharer_group > 1), every core of every flagged group — the
        conservative superset the directory actually knows."""
        G = self.cfg.sharer_group
        C = self.cfg.n_cores
        out = []
        for wi in range(sharers0.shape[3]):
            word = int(sharers0[b, s, w, wi])
            for bit in range(32):
                if word & (1 << bit):
                    g = wi * 32 + bit
                    out.extend(
                        t for t in range(g * G, min((g + 1) * G, C))
                    )
        return out

    def _any_sharer_bit(self, b, s, w) -> bool:
        return bool(self.sharers[b, s, w].any())

    # ----------------------------------------------------------------- run

    def run(self, max_steps: int = 10_000_000) -> None:
        for _ in range(max_steps):
            if self.done():
                return
            self.step()
        raise RuntimeError("golden: max_steps exceeded (deadlock?)")
