"""Golden reference simulator: slow, scalar, obviously correct.

Implements DESIGN.md's step semantics with plain Python/NumPy loops. This is
the oracle the vectorized JAX engine (`primesim_tpu/sim/engine.py`) must
match BIT-EXACTLY on per-core cycles, cache/directory state, and counters
(SURVEY.md §4: the single highest-value test asset the reference lacks).

Semantics map to the reference as: CoreManager per-core cycle accounting
(SURVEY.md §2 #2), Cache set-assoc lookup/LRU (#3), System directory-MESI
(#4), Network XY-hop latency (#6), Dram fixed latency (#7), and the relaxed
quantum barrier (#10) — all serialized here in the canonical deterministic
order DESIGN.md defines.
"""

from __future__ import annotations

import numpy as np

from ..config.machine import MachineConfig
from ..noc.mesh import bank_tile, core_tile, hops as _hops, one_way_lat, xy_links
from ..stats.counters import zero_counters
from ..trace.format import (
    EV_BARRIER,
    EV_END,
    EV_INS,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
    Trace,
)

# MESI encoding shared with the JAX engine
I, S, E, M = 0, 1, 2, 3


class GoldenSim:
    def __init__(self, cfg: MachineConfig, trace: Trace):
        assert trace.n_cores == cfg.n_cores
        self.cfg = cfg
        self.trace = trace
        # internal addressing is LINE-granular (same normalization as the
        # engine: byte traces shift at ingest, v4 line traces pass through)
        self.events = trace.line_events(cfg.line_bits)
        C, B = cfg.n_cores, cfg.n_banks
        l1s, l1w = cfg.l1.sets, cfg.l1.ways
        ls, lw = cfg.llc.sets, cfg.llc.ways

        self.cycles = np.zeros(C, dtype=np.int64)
        self.ptr = np.zeros(C, dtype=np.int64)
        self.cpi = np.array(cfg.core.cpi_vector(C), dtype=np.int64)

        self.l1_tag = np.full((C, l1s, l1w), -1, dtype=np.int64)
        self.l1_state = np.full((C, l1s, l1w), I, dtype=np.int64)
        self.l1_lru = np.zeros((C, l1s, l1w), dtype=np.int64)

        self.llc_tag = np.full((B, ls, lw), -1, dtype=np.int64)
        self.llc_owner = np.full((B, ls, lw), -1, dtype=np.int64)
        self.llc_lru = np.zeros((B, ls, lw), dtype=np.int64)
        # sharer bit-vector words, matching the JAX engine's packed layout
        self.sharers = np.zeros((B, ls, lw, cfg.n_sharer_words), dtype=np.uint32)

        self.counters = zero_counters(C)
        self.quantum_end = cfg.quantum
        self.step_count = 0

        # synchronization state (DESIGN.md §3 phase 2.7)
        self.lock_holder = np.full(cfg.lock_slots, -1, dtype=np.int64)
        self.barrier_count = np.zeros(cfg.barrier_slots, dtype=np.int64)
        self.barrier_time = np.zeros(cfg.barrier_slots, dtype=np.int64)
        self.sync_flag = np.zeros(C, dtype=np.int64)
        from ..trace.format import validate_sync

        validate_sync(trace, cfg.barrier_slots)

    # ------------------------------------------------------------ helpers

    def _bank(self, line: int) -> int:
        return line % self.cfg.n_banks

    def _bank_set(self, line: int) -> int:
        return (line // self.cfg.n_banks) % self.cfg.llc.sets

    def _l1_set(self, line: int) -> int:
        return line % self.cfg.l1.sets

    def _victim_way(self, tags, states, lrus):
        """Invalid-first LRU with lowest-index tie break (DESIGN.md §1)."""
        key = [(-1 if states[w] == I else int(lrus[w])) for w in range(len(tags))]
        return int(np.argmin(key))

    def _set_sharer(self, b, s, w, core, val: bool):
        wi, bit = core // 32, core % 32
        if val:
            self.sharers[b, s, w, wi] |= np.uint32(1 << bit)
        else:
            self.sharers[b, s, w, wi] &= np.uint32(~(1 << bit) & 0xFFFFFFFF)

    def _clear_sharers(self, b, s, w):
        self.sharers[b, s, w, :] = 0

    def _lock_slot(self, line: int) -> int:
        """Mutex LINE index -> lock-table slot (events are line-granular)."""
        return line & (self.cfg.lock_slots - 1)

    def _lock_home_tile(self, line: int) -> int:
        return bank_tile(self._bank(line), self.cfg)

    def _noc(self, c: int, tile_a: int, tile_b: int):
        """Charge one message tile_a->tile_b to core c's NoC counters."""
        lat = one_way_lat(tile_a, tile_b, self.cfg)
        self.counters["noc_msgs"][c] += 1
        self.counters["noc_hops"][c] += _hops(tile_a, tile_b, self.cfg.noc.mesh_x)
        return lat

    def _txn_path(self, ctile: int, htile: int, round_trip: bool) -> list[int]:
        mx = self.cfg.noc.mesh_x
        p = xy_links(ctile, htile, mx)
        if round_trip:
            p = p + xy_links(htile, ctile, mx)
        return p

    def _contention_extra(
        self, c: int, ctile: int, htile: int, round_trip: bool = True
    ) -> int:
        """Queueing charge for core c's transaction from `ctile` to home
        `htile` this step (0 when the model is disabled). Tile model:
        occupancy at the home tile; link model: bottleneck occupancy over
        the transaction's XY path links."""
        cfg = self.cfg
        if not cfg.noc.contention:
            return 0
        if cfg.noc.contention_model == "tile":
            extra = cfg.noc.contention_lat * (self._tile_txns.get(htile, 1) - 1)
        else:
            worst = 0
            for l in self._txn_path(ctile, htile, round_trip):
                worst = max(worst, self._link_cnt.get(l, 1) - 1)
            extra = cfg.noc.contention_lat * worst
        self.counters["noc_contention_cycles"][c] += extra
        return extra

    # --------------------------------------------------------------- step

    def done(self) -> bool:
        t = self.events
        return all(
            t[c, min(int(self.ptr[c]), self.trace.max_len - 1), 0] == EV_END
            for c in range(self.cfg.n_cores)
        )

    def step(self) -> None:
        cfg = self.cfg
        C = cfg.n_cores
        ev = self.events

        # --- quantum barrier (DESIGN.md §3): bump quantum_end if nobody
        # active. Barrier-frozen cores neither bump nor bound the quantum.
        cur = [ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)] for c in range(C)]
        not_done = [c for c in range(C) if cur[c][0] != EV_END]
        if not not_done:
            return

        def _frozen(c):
            return cur[c][0] == EV_BARRIER and self.sync_flag[c]

        countable = [c for c in not_done if not _frozen(c)]
        active = [c for c in countable if self.cycles[c] < self.quantum_end]
        if not active and countable:
            m = min(int(self.cycles[c]) for c in countable)
            self.quantum_end = (m // cfg.quantum + 1) * cfg.quantum
            active = [c for c in countable if self.cycles[c] < self.quantum_end]
        # Clock-window invariant (DESIGN.md §3-sync): every active core's
        # clock lies in [quantum_end - Q, quantum_end). The JAX engine's
        # packed arbitration keys (rel*C + core) REQUIRE this; asserting it
        # here makes every golden/parity test also an invariant check.
        assert all(
            self.cycles[c] >= self.quantum_end - cfg.quantum for c in active
        ), "clock-window invariant violated"

        step = self.step_count
        self.step_count += 1

        # --- phase 0.5: local runs (DESIGN.md §3) --------------------------
        # Each active core first retires up to `local_run_len` LOCAL events
        # (INS batches, L1 read hits, L1 write hits in E/M) in order, judged
        # against the live directory (which no run modifies — runs touch only
        # the core's own L1 row: LRU refresh, silent E->M) and the core's own
        # live L1 state. The run stops at the first non-local event, at the
        # quantum boundary, or after local_run_len events. The event then at
        # ptr enters the normal per-step phases below.
        for c in active:
            for _ in range(cfg.local_run_len):
                if self.cycles[c] >= self.quantum_end:
                    break
                e = ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
                t, arg, addr = int(e[0]), int(e[1]), int(e[2])
                pre = int(e[3])
                if t == EV_END:
                    break
                if t == EV_INS:
                    self.cycles[c] += arg * int(self.cpi[c])
                    self.counters["instructions"][c] += arg
                    self.ptr[c] += 1
                    continue
                if t not in (EV_LD, EV_ST):
                    break  # sync events are never local: arbitrate below
                line = addr  # line-granular events
                s = self._l1_set(line)
                w = -1
                for wy in range(cfg.l1.ways):
                    if (
                        self.l1_tag[c, s, wy] == line
                        and self.l1_state[c, s, wy] != I
                    ):
                        w = wy
                        break
                if w < 0:
                    break  # miss: stop the run, arbitrate below
                if t == EV_ST and self.l1_state[c, s, w] not in (E, M):
                    break  # held in S: upgrade request, arbitrate below
                self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                self.counters["instructions"][c] += pre + 1
                if t == EV_LD:
                    self.counters["l1_read_hits"][c] += 1
                else:
                    self.counters["l1_write_hits"][c] += 1
                    self.l1_state[c, s, w] = M  # silent E->M
                self.l1_lru[c, s, w] = step
                self.ptr[c] += 1
        if cfg.local_run_len:
            # re-gather events and the active set at the post-run pointers
            cur = [
                ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
                for c in range(C)
            ]
            active = [
                c
                for c in range(C)
                if cur[c][0] != EV_END
                and not _frozen(c)
                and self.cycles[c] < self.quantum_end
            ]

        # --- phase 0/1: classify against step-start state ------------------
        # Only the L1 tag/state arrays need step-start snapshots: phase-3
        # reads of OTHER cores' L1 rows (owner probes) must not see this
        # step's phase-A writes. Every other read in the step touches rows
        # that nothing else writes within the step (winners own their
        # (bank,set) exclusively; cores own their L1 row), so live arrays
        # are equivalent and the expensive LLC copies are skipped.
        l1_tag0 = self.l1_tag.copy()
        l1_state0 = self.l1_state.copy()

        # request tuple: (cycles, core, kind, line, pre)
        requests = []
        joins = []  # read-join candidates: (core, line, pre)
        lock_reqs = []  # (cycles, core, addr, pre)
        unlocks = []  # (core, addr, pre)
        barrier_arr = []  # (core, barrier id, n participants, pre)
        GETS, GETM, UPG = 0, 1, 2

        for c in active:
            t, arg, addr = int(cur[c][0]), int(cur[c][1]), int(cur[c][2])
            pre = int(cur[c][3])  # pre-batched non-memory instructions
            if t == EV_INS:
                self.cycles[c] += arg * int(self.cpi[c])
                self.counters["instructions"][c] += arg
                self.ptr[c] += 1
                continue
            if t == EV_LOCK:
                lock_reqs.append((int(self.cycles[c]), c, addr, pre))
                continue
            if t == EV_UNLOCK:
                unlocks.append((c, addr, pre))
                continue
            if t == EV_BARRIER:
                barrier_arr.append((c, addr, arg, pre))
                continue
            line = addr  # line-granular events
            s = self._l1_set(line)
            w = -1
            for wy in range(cfg.l1.ways):
                if l1_tag0[c, s, wy] == line and l1_state0[c, s, wy] != I:
                    w = wy
                    break
            if t == EV_LD:
                if w >= 0:  # read hit
                    self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                    self.counters["l1_read_hits"][c] += 1
                    self.counters["instructions"][c] += pre + 1
                    self.l1_lru[c, s, w] = step  # phase A local
                    self.ptr[c] += 1
                elif self._join_eligible(c, line):
                    joins.append((c, line, pre))
                else:
                    requests.append((int(self.cycles[c]), c, GETS, line, pre))
            else:  # EV_ST
                if w >= 0 and l1_state0[c, s, w] in (E, M):  # write hit
                    self.cycles[c] += pre * int(self.cpi[c]) + cfg.l1.latency
                    self.counters["l1_write_hits"][c] += 1
                    self.counters["instructions"][c] += pre + 1
                    self.l1_state[c, s, w] = M  # silent E->M, phase A local
                    self.l1_lru[c, s, w] = step
                    self.ptr[c] += 1
                elif w >= 0:  # held in S -> upgrade
                    requests.append((int(self.cycles[c]), c, UPG, line, pre))
                else:
                    requests.append((int(self.cycles[c]), c, GETM, line, pre))

        # --- phase 2: per-(bank,set) conflict serialization ----------------
        # Read-joins (GETS to a shared, ownerless, already-shared line)
        # coalesce: any number retire in one step, bit-exact to any
        # serialization order because the join path's latency is independent
        # of the sharer set and the sharer-bit updates commute (DESIGN.md
        # §3). A join only proceeds if no arbitrating request targets its
        # home (bank,set) this step; otherwise it demotes to a normal GETS.
        arb_slots = {
            (self._bank(r[3]), self._bank_set(r[3])) for r in requests
        }
        join_go = []
        for c, line, pre in joins:
            if (self._bank(line), self._bank_set(line)) in arb_slots:
                requests.append((int(self.cycles[c]), c, GETS, line, pre))
            else:
                join_go.append((c, line, pre))

        by_bankset: dict[tuple[int, int], list] = {}
        for r in requests:
            key = (self._bank(r[3]), self._bank_set(r[3]))
            by_bankset.setdefault(key, []).append(r)
        winners = []
        for key, rs in by_bankset.items():
            rs.sort(key=lambda r: (r[0], r[1]))  # (cycles, core_id)
            winners.append(rs[0])
            for r in rs[1:]:
                self.counters["retries"][r[1]] += 1

        # --- contention occupancy counts (NocConfig.contention) -----------
        # Tile model: every uncore transaction served at a home tile this
        # step queues behind the others there. Link model: every directed
        # mesh link on a transaction's XY request+reply path (barrier
        # arrivals: one-way) is claimed by it. Counts are fixed BEFORE any
        # charging so the extra is identical for every transaction sharing
        # a tile/link (matching the engine's one-scatter count). The
        # transaction classes: memory winners + joins (home bank),
        # lock/unlock RMWs (lock home), barrier arrivals (barrier home).
        self._tile_txns = {}
        self._link_cnt = {}
        if cfg.noc.contention:
            link_model = cfg.noc.contention_model == "link"

            def _bump(c, htile, round_trip=True):
                if link_model:
                    ctile = core_tile(c, cfg)
                    for l in self._txn_path(ctile, htile, round_trip):
                        self._link_cnt[l] = self._link_cnt.get(l, 0) + 1
                else:
                    self._tile_txns[htile] = self._tile_txns.get(htile, 0) + 1

            for _, c, _, line, _ in winners:
                _bump(c, bank_tile(self._bank(line), cfg))
            for c, line, _ in join_go:
                _bump(c, bank_tile(self._bank(line), cfg))
            for c, addr, _ in unlocks:
                _bump(c, self._lock_home_tile(addr))
            for _, c, addr, _ in lock_reqs:
                _bump(c, self._lock_home_tile(addr))
            for c, bid, _, _ in barrier_arr:
                _bump(c, bid % cfg.n_tiles, round_trip=False)

        for c, line, pre in join_go:
            self._do_join(c, line, pre, step)

        # --- phase 3: transitions on step-start state; collect phase-B ops -
        # Phase-B op = (core, line, op) with op in {"downgrade","invalidate"}
        phase_b: list[tuple[int, int, str]] = []

        for cyc, c, kind, line, pre in sorted(winners, key=lambda r: r[1]):
            b = self._bank(line)
            bs = self._bank_set(line)
            ctile = core_tile(c, cfg)
            btile = bank_tile(b, cfg)

            lat = cfg.l1.latency
            lat += self._noc(c, ctile, btile)  # request
            lat += cfg.llc.latency

            # LLC lookup (step-start)
            hitw = -1
            for wy in range(cfg.llc.ways):
                if self.llc_tag[b, bs, wy] == line:
                    hitw = wy
                    break

            if kind == GETS:
                self.counters["l1_read_misses"][c] += 1
            elif kind == GETM:
                self.counters["l1_write_misses"][c] += 1
            else:
                self.counters["upgrades"][c] += 1

            if hitw >= 0:
                self.counters["llc_hits"][c] += 1
                w = hitw
                owner = int(self.llc_owner[b, bs, w])
                shl = [
                    t
                    for t in self._sharers_from(self.sharers, b, bs, w)
                    if t != c
                ]
                if kind == GETS:
                    if owner >= 0 and owner != c:
                        # probe owner (charged regardless of staleness)
                        otile = core_tile(owner, cfg)
                        lat += self._noc(c, btile, otile)
                        lat += self._noc(c, otile, btile)
                        self.counters["probes"][c] += 1
                        phase_b.append((owner, line, "downgrade"))
                        self.llc_owner[b, bs, w] = -1
                        self._clear_sharers(b, bs, w)
                        self._set_sharer(b, bs, w, c, True)
                        # The directory cannot observe silent L1 evictions,
                        # so the probed owner is conservatively re-recorded
                        # as a sharer whether or not it still holds the line
                        # (recorded sharers stay a superset of holders) —
                        # exactly what a real home node does, and it keeps
                        # the home-side transition free of any read of the
                        # owner's private cache state.
                        self._set_sharer(b, bs, w, owner, True)
                        grant = S
                    elif shl:
                        self._set_sharer(b, bs, w, c, True)
                        grant = S
                    else:
                        self.llc_owner[b, bs, w] = c
                        self._clear_sharers(b, bs, w)
                        grant = E
                else:  # GETM or UPG
                    inv_lat = 0
                    if owner >= 0 and owner != c:
                        otile = core_tile(owner, cfg)
                        lat += self._noc(c, btile, otile)
                        lat += self._noc(c, otile, btile)
                        self.counters["probes"][c] += 1
                        phase_b.append((owner, line, "invalidate"))
                    for tcore in shl:
                        ttile = core_tile(tcore, cfg)
                        rt = one_way_lat(btile, ttile, cfg) * 2
                        inv_lat = max(inv_lat, rt)
                        self.counters["invalidations"][c] += 1
                        self.counters["noc_msgs"][c] += 2
                        self.counters["noc_hops"][c] += 2 * _hops(
                            btile, ttile, cfg.noc.mesh_x
                        )
                        phase_b.append((tcore, line, "invalidate"))
                    lat += inv_lat
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = M
                self.llc_lru[b, bs, w] = step
            else:
                # LLC miss -> DRAM + fill (UPG stale corner handled as GETM)
                self.counters["llc_misses"][c] += 1
                self.counters["dram_accesses"][c] += 1
                self.counters["noc_msgs"][c] += 2  # to co-located controller
                lat += cfg.dram_lat
                # victim selection on step-start state
                w = self._victim_way(
                    self.llc_tag[b, bs],
                    self._llc_valid(self.llc_tag, b, bs),
                    self.llc_lru[b, bs],
                )
                if self.llc_tag[b, bs, w] != -1:
                    vline = int(self.llc_tag[b, bs, w])
                    vowner = int(self.llc_owner[b, bs, w])
                    vtargets = self._sharers_from(self.sharers, b, bs, w)
                    if vowner >= 0:
                        self.counters["llc_writebacks"][c] += 1
                        if vowner not in vtargets:
                            vtargets = vtargets + [vowner]
                    for tcore in vtargets:
                        ttile = core_tile(tcore, cfg)
                        self.counters["invalidations"][c] += 1
                        self.counters["noc_msgs"][c] += 2
                        self.counters["noc_hops"][c] += 2 * _hops(
                            btile, ttile, cfg.noc.mesh_x
                        )
                        phase_b.append((tcore, vline, "invalidate"))
                self.llc_tag[b, bs, w] = line
                self.llc_lru[b, bs, w] = step
                if kind == GETS:
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = E
                else:
                    self.llc_owner[b, bs, w] = c
                    self._clear_sharers(b, bs, w)
                    grant = M

            lat += self._noc(c, btile, ctile)  # reply
            lat += self._contention_extra(c, ctile, btile)

            # O3-style overlap: hide a fraction of the miss latency
            ov = cfg.core.o3_overlap_256
            if ov:
                lat = lat - ((lat * ov) >> 8)

            # --- phase 4.A for this winner: L1 update ----------------------
            s = self._l1_set(line)
            curw = -1
            for wy in range(cfg.l1.ways):
                if l1_tag0[c, s, wy] == line and l1_state0[c, s, wy] != I:
                    curw = wy
                    break
            if kind == UPG and curw >= 0:
                self.l1_state[c, s, curw] = grant
                self.l1_lru[c, s, curw] = step
            else:
                vw = self._victim_way(
                    l1_tag0[c, s],
                    l1_state0[c, s],
                    self.l1_lru[c, s],
                )
                if l1_state0[c, s, vw] == M:
                    self.counters["l1_writebacks"][c] += 1
                self.l1_tag[c, s, vw] = line
                self.l1_state[c, s, vw] = grant
                self.l1_lru[c, s, vw] = step

            self.cycles[c] += pre * int(self.cpi[c]) + lat
            self.counters["instructions"][c] += pre + 1
            self.ptr[c] += 1

        # --- phase 4.B: remote ops, tag-conditional against live state -----
        for tcore, line, op in phase_b:
            s = self._l1_set(line)
            for wy in range(cfg.l1.ways):
                if self.l1_tag[tcore, s, wy] == line and self.l1_state[tcore, s, wy] != I:
                    if op == "downgrade":
                        if self.l1_state[tcore, s, wy] in (E, M):
                            self.l1_state[tcore, s, wy] = S
                    else:
                        self.l1_state[tcore, s, wy] = I
                    break

        # --- phase 2.7: synchronization events (DESIGN.md) -----------------
        # Sync and memory phases touch disjoint per-core/table state, so
        # their relative order within the step is immaterial; unlocks ->
        # lock grants -> barrier arrivals -> releases is the canonical
        # order WITHIN sync.
        for c, addr, pre in unlocks:
            s = self._lock_slot(addr)
            h = self._lock_home_tile(addr)
            ctile = core_tile(c, cfg)
            lat = self._noc(c, ctile, h) + cfg.llc.latency + self._noc(c, h, ctile)
            lat += self._contention_extra(c, ctile, h)
            self.cycles[c] += pre * int(self.cpi[c]) + lat
            self.counters["instructions"][c] += pre + 1
            if self.lock_holder[s] == c:
                self.lock_holder[s] = -1
            self.ptr[c] += 1

        by_slot: dict[int, list] = {}
        for r in lock_reqs:
            by_slot.setdefault(self._lock_slot(r[2]), []).append(r)
        for s, rs in sorted(by_slot.items()):
            rs.sort(key=lambda r: (r[0], r[1]))  # (cycles, core_id)
            for i, (cyc, c, addr, pre) in enumerate(rs):
                h = self._lock_home_tile(addr)
                ctile = core_tile(c, cfg)
                # every attempt (grant or spin) is a charged RMW round trip
                lat = (
                    self._noc(c, ctile, h)
                    + cfg.llc.latency
                    + self._noc(c, h, ctile)
                )
                lat += self._contention_extra(c, ctile, h)
                if self.sync_flag[c] == 0:  # first attempt: charge pre batch
                    self.cycles[c] += pre * int(self.cpi[c])
                    self.counters["instructions"][c] += pre
                self.cycles[c] += lat
                holder = int(self.lock_holder[s])
                if holder == c or (i == 0 and holder == -1):
                    self.lock_holder[s] = c
                    self.counters["lock_acquires"][c] += 1
                    self.counters["instructions"][c] += 1
                    self.sync_flag[c] = 0
                    self.ptr[c] += 1
                else:
                    self.counters["lock_spins"][c] += 1
                    self.sync_flag[c] = 1

        for c, bid, n, pre in barrier_arr:
            h = bid % cfg.n_tiles
            ctile = core_tile(c, cfg)
            self.cycles[c] += pre * int(self.cpi[c])
            self.counters["instructions"][c] += pre
            self.cycles[c] += self._noc(c, ctile, h)  # arrival message
            self.cycles[c] += self._contention_extra(c, ctile, h, round_trip=False)
            self.counters["barrier_waits"][c] += 1
            self.sync_flag[c] = 1
            self.barrier_count[bid] += 1
            self.barrier_time[bid] = max(
                int(self.barrier_time[bid]), int(self.cycles[c])
            )

        # releases: every waiter whose slot count reached ITS participant
        # count resumes at the slot's max arrival time + wake-up message
        waiting: dict[int, list] = {}
        for c in range(C):
            e = ev[c, min(int(self.ptr[c]), self.trace.max_len - 1)]
            if int(e[0]) == EV_BARRIER and self.sync_flag[c]:
                waiting.setdefault(int(e[2]), []).append((c, int(e[1])))
        for bid, ws in sorted(waiting.items()):
            rel = [c for c, n in ws if self.barrier_count[bid] >= n]
            for c in rel:
                h = bid % cfg.n_tiles
                ctile = core_tile(c, cfg)
                self.cycles[c] = int(self.barrier_time[bid]) + self._noc(
                    c, h, ctile
                )
                self.counters["instructions"][c] += 1
                self.sync_flag[c] = 0
                self.ptr[c] += 1
            self.barrier_count[bid] -= len(rel)
            if self.barrier_count[bid] <= 0:
                self.barrier_count[bid] = 0
                self.barrier_time[bid] = 0

    # ------------------------------------------------------ read-join path

    def _join_eligible(self, c: int, line: int) -> bool:
        """GETS may coalesce iff the line is LLC-resident, ownerless, and
        already shared by someone else (DESIGN.md §3 'plain join' case —
        the only transition whose outcome and latency are independent of
        concurrent same-line readers)."""
        b, bs = self._bank(line), self._bank_set(line)
        for wy in range(self.cfg.llc.ways):
            if self.llc_tag[b, bs, wy] == line:
                if self.llc_owner[b, bs, wy] >= 0:
                    return False
                shl = self._sharers_from(self.sharers, b, bs, wy)
                return any(t != c for t in shl)
        return False

    def _do_join(self, c: int, line: int, pre: int, step: int) -> None:
        """Retire one coalesced read-join (same outcome as the serialized
        'sharers non-empty -> S, sharers |= {c}' path)."""
        cfg = self.cfg
        b, bs = self._bank(line), self._bank_set(line)
        ctile, btile = core_tile(c, cfg), bank_tile(b, cfg)
        w = -1
        for wy in range(cfg.llc.ways):
            if self.llc_tag[b, bs, wy] == line:
                w = wy
                break
        self.counters["l1_read_misses"][c] += 1
        self.counters["llc_hits"][c] += 1
        lat = cfg.l1.latency
        lat += self._noc(c, ctile, btile)
        lat += cfg.llc.latency
        self._set_sharer(b, bs, w, c, True)
        self.llc_lru[b, bs, w] = step
        lat += self._noc(c, btile, ctile)
        lat += self._contention_extra(c, ctile, btile)
        ov = cfg.core.o3_overlap_256
        if ov:
            lat = lat - ((lat * ov) >> 8)
        # L1 fill (victim on step-start state == live state for this set:
        # joins are this core's only action this step)
        s = self._l1_set(line)
        vw = self._victim_way(
            self.l1_tag[c, s], self.l1_state[c, s], self.l1_lru[c, s]
        )
        if self.l1_state[c, s, vw] == M:
            self.counters["l1_writebacks"][c] += 1
        self.l1_tag[c, s, vw] = line
        self.l1_state[c, s, vw] = S
        self.l1_lru[c, s, vw] = step
        self.cycles[c] += pre * int(self.cpi[c]) + lat
        self.counters["instructions"][c] += pre + 1
        self.ptr[c] += 1

    # ----------------------------------------------------- static helpers

    def _llc_valid(self, llc_tag0, b, bs):
        """Map tags to pseudo-states for victim selection (valid=1, I=0)."""
        return [I if llc_tag0[b, bs, w] == -1 else S for w in range(self.cfg.llc.ways)]

    def _sharers_from(self, sharers0, b, s, w) -> list[int]:
        out = []
        for wi in range(sharers0.shape[3]):
            word = int(sharers0[b, s, w, wi])
            for bit in range(32):
                if word & (1 << bit):
                    out.append(wi * 32 + bit)
        return out

    # ----------------------------------------------------------------- run

    def run(self, max_steps: int = 10_000_000) -> None:
        for _ in range(max_steps):
            if self.done():
                return
            self.step()
        raise RuntimeError("golden: max_steps exceeded (deadlock?)")
