"""Chrome trace-event flight recorder.

Emits the JSON Object Format (``{"traceEvents": [...]}``) understood by
Perfetto and chrome://tracing. One process (`pid` = os.getpid()), one
synthetic thread per event source — "engine", "supervisor", "scheduler",
"journal", per-bucket fleet labels — named via `ph:"M"` thread_name
metadata so the timeline rows read like the subsystems they are.

Invariants the schema test (tests/test_obs.py) holds us to:

- every event has ``ph``, ``ts``, ``pid``, ``tid``, ``name``
- ``ts`` is non-decreasing per tid
- B/E spans are balanced per tid (we only emit non-nested spans, so
  balanced == alternating B,E,B,E...)

Spans are recorded retroactively: callers time a region themselves and
hand us the duration (`complete()`), so the hot loop pays one
perf_counter call per phase, not a writer call on entry AND exit. To
keep per-tid timestamps monotonic even when a caller's span would
overlap the previous one (clock jitter), the B timestamp is clamped to
the previous span's end on that tid.
"""

from __future__ import annotations

import json
import os
import time


class TraceWriter:
    def __init__(self, max_events: int = 200_000):
        self.t0 = time.perf_counter()
        self.pid = os.getpid()
        self.max_events = int(max_events)
        self.events = []
        self.dropped = 0
        self._tids = {}
        self._last_end_us = {}

    def _now_us(self):
        return (time.perf_counter() - self.t0) * 1e6

    def _tid(self, label):
        tid = self._tids.get(label)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[label] = tid
            self._last_end_us[tid] = 0.0
            # thread_name metadata so Perfetto labels the row
            self.events.append({
                "ph": "M", "ts": 0, "pid": self.pid, "tid": tid,
                "name": "thread_name", "args": {"name": str(label)},
            })
        return tid

    def _push(self, ev):
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return False
        self.events.append(ev)
        return True

    def complete(self, label, name, dur_s, args=None):
        """Record a span of ``dur_s`` seconds ending now on ``label``'s row."""
        tid = self._tid(label)
        end = self._now_us()
        begin = max(end - float(dur_s) * 1e6, self._last_end_us[tid])
        if begin > end:  # clamp collapsed the span; keep it zero-width
            begin = end
        b = {"ph": "B", "ts": begin, "pid": self.pid, "tid": tid, "name": str(name)}
        if args:
            b["args"] = dict(args)
        e = {"ph": "E", "ts": end, "pid": self.pid, "tid": tid, "name": str(name)}
        # push pairwise so B/E stay balanced even at the drop boundary
        if len(self.events) + 2 > self.max_events:
            self.dropped += 2
            return
        self.events.append(b)
        self.events.append(e)
        self._last_end_us[tid] = end

    def instant(self, label, name, args=None):
        tid = self._tid(label)
        ts = max(self._now_us(), self._last_end_us[tid])
        ev = {"ph": "i", "ts": ts, "pid": self.pid, "tid": tid,
              "name": str(name), "s": "t"}
        if args:
            ev["args"] = dict(args)
        if self._push(ev):
            self._last_end_us[tid] = ts

    def write(self, path):
        doc = {
            "traceEvents": self.events,
            "displayTimeUnit": "ms",
        }
        if self.dropped:
            doc["otherData"] = {"dropped_events": self.dropped}
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(self.events)
