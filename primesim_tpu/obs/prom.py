"""Prometheus text-exposition renderer for the serve daemon.

Pure formatting over the scheduler's live state — no collection happens
here (the scheduler/journal already maintain the counters and
histograms), so rendering is safe to call from the tick loop at any
time. Output follows the text exposition format version 0.0.4:
``# HELP`` / ``# TYPE`` headers, histograms as cumulative ``_bucket``
series with an explicit ``+Inf`` bucket plus ``_sum``/``_count``.
"""

from __future__ import annotations

import time


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v != v:  # NaN
            return "NaN"
        return repr(v)
    return str(v)


class _Doc:
    def __init__(self):
        self.lines = []

    def metric(self, name, mtype, help_text, samples):
        """samples: list of (labels_dict_or_None, value)."""
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if labels:
                lab = ",".join(
                    f'{k}="{v}"' for k, v in labels.items()
                )
                self.lines.append(f"{name}{{{lab}}} {_fmt(value)}")
            else:
                self.lines.append(f"{name} {_fmt(value)}")

    def histogram(self, name, help_text, hist):
        snap = hist.snapshot()
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} histogram")
        for bound, cum in zip(snap["bounds"], snap["cumulative"]):
            self.lines.append(f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        self.lines.append(f'{name}_bucket{{le="+Inf"}} {snap["count"]}')
        self.lines.append(f"{name}_sum {_fmt(snap['sum'])}")
        self.lines.append(f"{name}_count {snap['count']}")

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_prometheus(sched, journal=None, draining=False,
                      recovered=None, quota=None, repl=None) -> str:
    """Render the daemon's scrape payload from a live Scheduler (and
    optionally its JobJournal + the server's recovery/drain state)."""
    s = sched.stats()
    d = _Doc()

    d.metric("primetpu_queue_depth", "gauge",
             "Jobs pending in the bounded admission queue.",
             [(None, s["queue_depth"])])
    d.metric("primetpu_slots", "gauge",
             "Fleet slots by bucket and occupancy.",
             [({"pages": str(b["pages"]), "state": "occupied"},
               b["occupied"]) for b in s["slots"]["buckets"]]
             + [({"pages": str(b["pages"]), "state": "free"},
                 b["slots"] - b["occupied"])
                for b in s["slots"]["buckets"]])
    d.metric("primetpu_slots_total", "gauge",
             "Total fleet slots across all buckets.",
             [(None, s["slots"]["total"])])
    d.metric("primetpu_slots_occupied", "gauge",
             "Occupied fleet slots across all buckets.",
             [(None, s["slots"]["occupied"])])
    d.metric("primetpu_jobs", "gauge",
             "Jobs in the table by lifecycle state.",
             [({"state": st}, n) for st, n in sorted(s["jobs"].items())])
    d.metric("primetpu_jobs_completed_total", "counter",
             "Jobs retired DONE since daemon start.",
             [(None, s["completed"])])
    d.metric("primetpu_instructions_total", "counter",
             "Simulated instructions retired across all completed jobs.",
             [(None, sched.total_instructions)])
    d.metric("primetpu_aggregate_mips", "gauge",
             "Simulated MIPS aggregated over daemon uptime.",
             [(None, s["aggregate_mips"])])
    d.metric("primetpu_uptime_seconds", "gauge",
             "Seconds since daemon start.",
             [(None, s["uptime_s"])])
    d.metric("primetpu_draining", "gauge",
             "1 while the daemon is draining for shutdown.",
             [(None, 1 if draining else 0)])
    d.metric("primetpu_promotions_total", "counter",
             "Windowed jobs migrated UP to a larger capacity bucket "
             "before reaching the window edge (v2 paged allocator).",
             [(None, getattr(sched, "promotions", 0))])
    d.metric("primetpu_demotions_total", "counter",
             "Jobs migrated DOWN to a smaller bucket to unblock a "
             "queued job that only fits the larger one.",
             [(None, getattr(sched, "demotions", 0))])
    d.metric("primetpu_quota_rejections_total", "counter",
             "Submits rejected by per-tenant admission quotas.",
             [(None, quota.rejections if quota is not None else 0)])
    workers = (s.get("workers") or {})
    if workers:
        d.metric("primetpu_dispatch_workers", "gauge",
                 "Live pool-worker processes owned by this front-end "
                 "(dispatch mode).",
                 [({"state": "live"}, workers.get("live", 0)),
                  ({"state": "max"}, workers.get("max", 0))])
        d.metric("primetpu_dispatch_coordinator_adopted", "gauge",
                 "1 when this front-end ADOPTED a live coordinator "
                 "instead of spawning one (standby takeover).",
                 [(None, 1 if workers.get("coordinator_adopted") else 0)])

    last_t = getattr(sched, "last_dispatch_t", None)
    age = (time.time() - last_t) if last_t else float("nan")
    d.metric("primetpu_last_dispatch_age_seconds", "gauge",
             "Seconds since a job was last placed into a slot "
             "(NaN before the first dispatch).",
             [(None, age)])

    hist = getattr(sched, "latency_hist", None)
    if hist is not None:
        d.histogram("primetpu_job_latency_seconds",
                    "Accept-to-terminal latency of finished jobs.", hist)

    if journal is not None:
        d.metric("primetpu_journal_appends_total", "counter",
                 "Journal records fsynced since daemon start.",
                 [(None, journal.appended)])
        fsync = getattr(journal, "fsync_hist", None)
        if fsync is not None:
            d.histogram("primetpu_journal_fsync_seconds",
                        "Wall time of each journal write+flush+fsync.",
                        fsync)

    if repl is not None:
        rs = repl.status()
        d.metric("primetpu_replication_links", "gauge",
                 "Replica links by connection state.",
                 [({"state": "connected"},
                   sum(1 for r in rs["replicas"] if r["connected"])),
                  ({"state": "configured"}, len(rs["replicas"]))])
        d.metric("primetpu_replication_quorum_ok", "gauge",
                 "1 while the last quorum round reached the configured "
                 "replica-ack quorum (0 = degraded or blocking).",
                 [(None, 1 if rs["quorum_ok"] else 0)])
        d.metric("primetpu_replication_epoch", "gauge",
                 "Fencing epoch of this primary's reign.",
                 [(None, rs["epoch"])])
        d.metric("primetpu_replication_fenced", "gauge",
                 "1 once a higher epoch deposed this primary "
                 "(it stops ACKing and exits 75).",
                 [(None, 1 if rs["fenced"] else 0)])
        d.metric("primetpu_replication_degraded_acks_total", "counter",
                 "Appends ACKed on local fsync only while below quorum "
                 "(--quorum-policy degrade).",
                 [(None, rs["degraded_acks"])])
        d.metric("primetpu_replication_quorum_losses_total", "counter",
                 "Quorum rounds that fell short of the required "
                 "replica acks.", [(None, rs["quorum_losses"])])
        d.metric("primetpu_replication_resyncs_total", "counter",
                 "Follower catch-up resyncs pushed by this primary.",
                 [(None, rs["resyncs"])])

    if recovered:
        d.metric("primetpu_recovered_jobs", "gauge",
                 "Jobs recovered from the journal at startup.",
                 [({"kind": "replayed"},
                   recovered.get("jobs_replayed", 0)),
                  ({"kind": "requeued"},
                   recovered.get("jobs_requeued", 0))])

    return d.render()


def render_pool_prometheus(coord) -> str:
    """Scrape payload for a live pool coordinator (`metrics` verb on the
    pool socket — `primetpu serve-status --metrics` works against it)."""
    s = coord.stats()
    d = _Doc()

    d.metric("primetpu_pool_units", "gauge",
             "Work units by lease-lifecycle state.",
             [({"state": st}, n) for st, n in sorted(s["units"].items())])
    d.metric("primetpu_pool_leases_active", "gauge",
             "Leases currently held by workers (hedges count twice).",
             [(None, s["leases_active"])])
    d.metric("primetpu_pool_workers_seen", "gauge",
             "Distinct worker ids that have ever requested a lease.",
             [(None, len(s["workers_seen"]))])
    c = s["counters"]
    d.metric("primetpu_pool_leases_total", "counter",
             "Leases granted since campaign start.",
             [(None, c["leases"])])
    d.metric("primetpu_pool_expired_total", "counter",
             "Leases expired for missed heartbeats (presumed-dead "
             "workers).", [(None, c["expired"])])
    d.metric("primetpu_pool_redispatches_total", "counter",
             "Units re-dispatched after a lease expiry.",
             [(None, c["redispatches"])])
    d.metric("primetpu_pool_hedges_total", "counter",
             "Speculative straggler re-dispatches (first-ACK-wins).",
             [(None, c["hedges"])])
    d.metric("primetpu_pool_acks_total", "counter",
             "Unit results accepted.", [(None, c["acks"])])
    d.metric("primetpu_pool_duplicate_acks_total", "counter",
             "Acks discarded because another attempt already won.",
             [(None, c["duplicates"])])
    d.metric("primetpu_pool_poisoned_total", "counter",
             "Units quarantined after killing distinct workers.",
             [(None, c["poisoned"])])
    d.metric("primetpu_pool_heartbeats_total", "counter",
             "Heartbeats received.", [(None, c["heartbeats"])])
    d.metric("primetpu_pool_readoptions_total", "counter",
             "Live worker leases re-adopted by heartbeat epoch after a "
             "coordinator restart (failover without re-simulation).",
             [(None, c.get("readoptions", 0))])
    d.metric("primetpu_pool_enqueued_total", "counter",
             "Work units accepted via the dynamic enqueue verb.",
             [(None, c.get("enqueued", 0))])
    rec = s.get("recovered") or {}
    if rec:
        d.metric("primetpu_pool_recovered", "gauge",
                 "Ledger replay results from the last coordinator start.",
                 [({"kind": "units_respawned"},
                   rec.get("units_respawned", 0)),
                  ({"kind": "results_adopted"},
                   rec.get("results_adopted", 0))])
    d.metric("primetpu_pool_done", "gauge",
             "1 when every unit is DONE or POISON.",
             [(None, 1 if s["done"] else 0)])

    journal = getattr(coord, "journal", None)
    if journal is not None:
        d.metric("primetpu_journal_appends_total", "counter",
                 "Ledger records fsynced since campaign start.",
                 [(None, journal.appended)])
        fsync = getattr(journal, "fsync_hist", None)
        if fsync is not None:
            d.histogram("primetpu_journal_fsync_seconds",
                        "Wall time of each ledger write+flush+fsync.",
                        fsync)

    return d.render()
