"""The Recorder: one telemetry sink per run.

The engines don't know about files or trace formats — they carry a
plain ``obs`` attribute (None by default) and, when it is set, hand the
Recorder one call per committed chunk with the wall time, phase
timings, and their cumulative host counters. The Recorder turns the
cumulative counters into per-chunk DELTAS (keyed per engine label, so a
fleet's buckets and a solo engine never cross wires), feeds the ring
buffer, and — at level ``full`` — mirrors each chunk as a span in the
Chrome trace.

Levels:

- ``off``   — no Recorder is constructed at all; every engine-side
  telemetry branch is a single ``is not None`` check that fails. The
  fused `run()` paths never see a Recorder either way; `--obs off`
  therefore cannot perturb results (bit-exact by construction).
- ``basic`` — metric time-series only (ring buffer + JSONL dump).
- ``full``  — basic + flight recorder (Chrome trace JSON).
"""

from __future__ import annotations

import time

from .metrics import MetricStore
from .trace import TraceWriter

LEVELS = ("off", "basic", "full")


class Recorder:
    def __init__(self, level: str, capacity: int = 4096,
                 trace_path=None, metrics_path=None):
        if level not in LEVELS:
            raise ValueError(
                f"obs level must be one of {'|'.join(LEVELS)}, got {level!r}"
            )
        self.level = level
        self.enabled = level != "off"
        self.tracing = level == "full"
        self.trace_path = trace_path
        self.metrics_path = metrics_path
        self.store = MetricStore(capacity=capacity) if self.enabled else None
        self.trace = TraceWriter() if self.tracing else None
        self._prev_totals: dict[str, dict] = {}
        self._finalized = None

    # ---- engine side -----------------------------------------------------

    def attach(self, engine, label=None) -> None:
        """Point an engine's ``obs`` attribute at this recorder. Safe on
        Engine, FleetEngine, and StreamEngine alike."""
        if label is not None:
            engine.obs_label = label
        engine.obs = self

    def chunk_committed(self, label, steps, wall_s, host_counters,
                        phases=None) -> None:
        """One committed chunk from an engine loop.

        ``host_counters`` is the engine's CUMULATIVE counter dict —
        values may be int64 scalars per core ([C]) or per element+core
        ([B, C]); we total them and diff against the previous totals for
        this label.
        """
        totals = {k: int(v.sum()) for k, v in host_counters.items()}
        prev = self._prev_totals.get(label)
        if prev is None:
            deltas = totals
        else:
            deltas = {k: v - prev.get(k, 0) for k, v in totals.items()}
        self._prev_totals[label] = totals
        self.store.record(time.time(), label, steps, wall_s, deltas,
                          phases=phases)
        if self.trace is not None:
            args = {"steps": int(steps),
                    "instructions": deltas.get("instructions", 0)}
            if phases:
                args.update({f"{k}_ms": round(v * 1e3, 3)
                             for k, v in phases.items()})
            self.trace.complete(label, "chunk", wall_s, args)

    # ---- supervisor / serve side ----------------------------------------

    def supervisor_event(self, kind, msg) -> None:
        if self.trace is not None:
            self.trace.instant("supervisor", kind, {"msg": str(msg)})

    def serve_event(self, kind, args=None) -> None:
        if self.trace is not None:
            self.trace.instant("scheduler", kind, args)

    def fsync_event(self, wall_s) -> None:
        if self.trace is not None:
            self.trace.complete("journal", "fsync", wall_s)

    def prefix_event(self, kind, **args) -> None:
        """Warm-cache / prefix-fork instant (hit, miss, store, corrupt
        fallback) on the ``prefix`` track — the TIMELINE's evidence that
        a campaign skipped (or paid for) its shared prefix."""
        if self.trace is not None:
            self.trace.instant(
                "prefix", kind, {k: str(v) for k, v in args.items()}
            )

    def chaos_event(self, site, action, **args) -> None:
        """Injected-fault instant on the ``chaos`` track — every fault a
        FaultPlan fires lands here, so a chaotic run's TIMELINE shows
        exactly what broke, where, and in what order."""
        if self.trace is not None:
            self.trace.instant(
                "chaos", f"{site}:{action}",
                {k: str(v) for k, v in args.items()}
            )

    def pool_event(self, kind, **args) -> None:
        """Elastic-pool instant (lease, heartbeat, expire, redispatch,
        hedge, ack, duplicate, poison) on the ``pool`` track — the
        TIMELINE's evidence of every lease-protocol decision, and what
        the chaos tests assert redispatch visibility against."""
        if self.trace is not None:
            self.trace.instant(
                "pool", kind, {k: str(v) for k, v in args.items()}
            )

    def repl_event(self, kind, **args) -> None:
        """Replication instant (epoch, resync, fenced, quorum-lost) on
        the ``repl`` track — the TIMELINE's evidence of every fencing
        and catch-up decision the primary's sink made."""
        if self.trace is not None:
            self.trace.instant(
                "repl", kind, {k: str(v) for k, v in args.items()}
            )

    # ---- output ----------------------------------------------------------

    def timeline_summary(self):
        """MetricStore summary for the report's TIMELINE section (None
        when nothing was recorded)."""
        if self.store is None:
            return None
        return self.store.summary()

    def finalize(self):
        """Write the configured output files. Idempotent — the CLI calls
        this on both the normal and the Preempted exit path."""
        if self._finalized is not None:
            return self._finalized
        written = {}
        if self.metrics_path and self.store is not None:
            written["metrics"] = (self.metrics_path,
                                  self.store.dump_jsonl(self.metrics_path))
        if self.trace_path and self.trace is not None:
            written["trace"] = (self.trace_path,
                                self.trace.write(self.trace_path))
        self._finalized = written
        return written
