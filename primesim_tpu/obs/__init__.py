"""Unified telemetry subsystem (DESIGN.md §15).

Three coupled layers, all host-side and all strictly read-only with
respect to the simulated machine (the device computation is untouched,
so `--obs off` is bit-exact by construction and `basic`/`full` only add
host bookkeeping at chunk boundaries the engines already cross):

- **Metric time-series** (`metrics.MetricStore`): a bounded ring buffer
  of per-chunk samples — counter DELTAS plus wall-clock phase timings —
  fed by the engine/fleet/stream chunk loops; dumpable as JSONL.
- **Flight recorder** (`trace.TraceWriter`): Chrome trace-event JSON
  (loads in Perfetto / chrome://tracing) with B/E spans for sim chunks,
  instant events for supervisor decisions (checkpoint, retry, preempt,
  guard, chaos) and serve scheduler events (admit, dispatch, retire,
  per-job checkpoint, journal fsync) — one correlated timeline across
  engine, supervisor, and daemon.
- **Serve metrics surface** (`prom.render_prometheus`): Prometheus
  text exposition over the scheduler's live stats (queue depth, jobs by
  state, per-bucket occupancy, latency histogram, journal fsync
  latency, throughput) — the `metrics` protocol verb and
  `serve-status --watch` render the same numbers.

`Recorder` is the facade the CLI wires in: one per run, levels
`off|basic|full` (off = no Recorder at all — engines carry a plain
`obs = None` attribute and skip every telemetry branch).
"""

from .metrics import Histogram, MetricStore
from .prom import render_prometheus
from .recorder import LEVELS, Recorder
from .trace import TraceWriter

__all__ = [
    "Histogram",
    "LEVELS",
    "MetricStore",
    "Recorder",
    "TraceWriter",
    "render_prometheus",
]
