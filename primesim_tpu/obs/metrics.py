"""Per-chunk metric time-series: bounded ring buffer + fixed-bucket histogram.

Deliberately numpy/jax-free so the serve daemon and the report renderer
can import it without touching the device runtime.
"""

from __future__ import annotations

import json
from collections import deque


class MetricStore:
    """Bounded ring buffer of per-chunk samples.

    Each sample is a plain dict::

        {"seq": int, "t": float, "label": str, "steps": int,
         "wall_s": float, "deltas": {counter: int, ...},
         "phases": {phase: float, ...}}   # phases optional

    ``seq`` is a global monotonically increasing chunk index (it keeps
    counting even after the ring starts dropping, so the slowest-chunk
    index in a summary refers to the real chunk number of the run).
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"MetricStore capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.seq = 0
        self.dropped = 0

    def record(self, t, label, steps, wall_s, deltas, phases=None):
        if len(self._ring) == self._ring.maxlen:
            self.dropped += 1
        sample = {
            "seq": self.seq,
            "t": float(t),
            "label": str(label),
            "steps": int(steps),
            "wall_s": float(wall_s),
            "deltas": {k: int(v) for k, v in deltas.items()},
        }
        if phases:
            sample["phases"] = {k: float(v) for k, v in phases.items()}
        self._ring.append(sample)
        self.seq += 1
        return sample

    def samples(self):
        return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def summary(self):
        """Aggregate view for the report TIMELINE section.

        MIPS here is *simulated instructions retired per wall second*
        for a chunk: deltas["instructions"] / wall_s / 1e6 — the same
        definition the end-of-run report uses, just per chunk.
        """
        if not self._ring:
            return None
        peak = mean_num = mean_den = 0.0
        peak_seq = slowest_seq = -1
        slowest_wall = -1.0
        total_steps = total_ins = 0
        labels: dict = {}
        for s in self._ring:
            ins = s["deltas"].get("instructions", 0)
            wall = s["wall_s"]
            total_steps += s["steps"]
            total_ins += ins
            lab = labels.setdefault(
                s["label"],
                {"chunks": 0, "steps": 0, "wall_s": 0.0, "instructions": 0},
            )
            lab["chunks"] += 1
            lab["steps"] += s["steps"]
            lab["wall_s"] += wall
            lab["instructions"] += ins
            if wall > 0:
                mips = ins / wall / 1e6
                if mips > peak:
                    peak, peak_seq = mips, s["seq"]
                mean_num += ins
                mean_den += wall
            if wall > slowest_wall:
                slowest_wall, slowest_seq = wall, s["seq"]
        return {
            "labels": labels,
            "chunks": self.seq,
            "retained": len(self._ring),
            "dropped": self.dropped,
            "total_steps": total_steps,
            "total_instructions": total_ins,
            "peak_chunk_mips": peak,
            "peak_chunk_seq": peak_seq,
            "mean_chunk_mips": (mean_num / mean_den / 1e6) if mean_den > 0 else 0.0,
            "slowest_chunk_seq": slowest_seq,
            "slowest_chunk_wall_s": slowest_wall,
        }

    def dump_jsonl(self, path):
        with open(path, "w") as f:
            for s in self._ring:
                f.write(json.dumps(s, sort_keys=True) + "\n")
        return len(self._ring)


# Default bucket bounds (seconds) shared by the serve latency and fsync
# histograms: roughly log-spaced from 1 ms to ~2 min, fine enough near
# the fsync floor and wide enough for multi-chunk job latencies.
DEFAULT_BOUNDS_S = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


class Histogram:
    """Fixed-bound cumulative histogram, Prometheus-shaped.

    ``counts[i]`` is the number of observations <= bounds[i] (cumulative,
    as Prometheus expects); observations above the last bound only land
    in the implicit +Inf bucket (``count``).
    """

    def __init__(self, bounds=DEFAULT_BOUNDS_S):
        self.bounds = tuple(float(b) for b in bounds)
        if any(b2 <= b1 for b1, b2 in zip(self.bounds, self.bounds[1:])):
            raise ValueError("histogram bounds must be strictly increasing")
        self._bucket_counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        for i, b in enumerate(self.bounds):
            if v <= b:
                self._bucket_counts[i] += 1
                break

    def snapshot(self):
        cum = []
        running = 0
        for c in self._bucket_counts:
            running += c
            cum.append(running)
        return {
            "bounds": list(self.bounds),
            "cumulative": cum,
            "count": self.count,
            "sum": self.sum,
        }
