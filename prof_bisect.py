"""Bisect per-step cost: stub out pieces of engine.step via source surgery.

Each variant knocks out ONE piece of the step (replacing it with a cheap
stand-in of the same shape) and times a 256-step `run_chunk` at the
flagship 1024-core config. The simulated behavior diverges under ablation
(that's fine — step cost is shape-static, not data-dependent), so this is
a TIMING tool only. Patterns are exact substrings of the current
`engine.py`; `build()` asserts they still exist so the tool rots loudly,
not silently (round-2 lesson).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import primesim_tpu.sim.engine as eng_mod
from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins

SRC = open(eng_mod.__file__).read()

VARIANTS = {
    "full": [],
    "no_sharers_scatter": [
        ('sharers_n = st.sharers.at[upd_slot].add(delta_row, mode="drop")',
         "sharers_n = st.sharers"),
    ],
    "no_llc_scatter": [
        ('llc_tag_n = st.llc_tag.at[wbank, bset, llc_uway].set(line, mode="drop")',
         "llc_tag_n = st.llc_tag"),
        ('llc_lru_n = st.llc_lru.at[lru_bank, bset, lru_way].set(step_no, mode="drop")',
         "llc_lru_n = st.llc_lru"),
        ('llc_owner_n = st.llc_owner.at[wbank, bset, llc_uway].set(new_owner, mode="drop")',
         "llc_owner_n = st.llc_owner"),
    ],
    "no_unpack_CC": [
        ("        sh_bits = unpack_bits(shw)",
         "        sh_bits = jnp.zeros((C, C), bool)"),
        ("        vic_sh_bits = unpack_bits(vic_shw)",
         "        vic_sh_bits = jnp.zeros((C, C), bool)"),
    ],
    "no_CC_reductions": [
        ("        inv_lat = jnp.max(jnp.where(inv_pairs, 2 * pair_lat, 0), axis=1)",
         "        inv_lat = jnp.zeros(C, jnp.int32)"),
        ("        inv_count = jnp.sum(inv_pairs, axis=1).astype(jnp.int32)",
         "        inv_count = jnp.zeros(C, jnp.int32)"),
        ("        inv_hops = jnp.sum(jnp.where(inv_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)",
         "        inv_hops = jnp.zeros(C, jnp.int32)"),
        ("        back_count = jnp.sum(back_pairs, axis=1).astype(jnp.int32)",
         "        back_count = jnp.zeros(C, jnp.int32)"),
        ("        back_hops = jnp.sum(jnp.where(back_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)",
         "        back_hops = jnp.zeros(C, jnp.int32)"),
    ],
    "no_arb_table": [
        ('    table = table.at[jnp.where(req, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
        ('    table = table.at[jnp.where(demoted, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
    ],
    "no_l1_scatters": [
        ('    l1_tag = st.l1_tag.at[dup_row, dup_col].set(-1, mode="drop")',
         "    l1_tag = st.l1_tag"),
        ('    l1_state = l1_state_c.at[dup_row, dup_col].set(I, mode="drop")',
         "    l1_state = l1_state_c"),
        ('    l1_lru = l1_lru_c.at[lru_row, lru_col].set(step_no, mode="drop")',
         "    l1_lru = l1_lru_c"),
        ('    l1_state = l1_state.at[st_row, st_col].set(st_val, mode="drop")',
         "    l1_state = l1_state"),
        ('    l1_tag = l1_tag.at[wj_row, upd_col].set(line, mode="drop")',
         "    l1_tag = l1_tag"),
    ],
    "no_l1ptr_write": [
        ('    l1_ptr = st.l1_ptr.at[wj_row, upd_col].set(fill_ptr, mode="drop")',
         "    l1_ptr = st.l1_ptr"),
    ],
    "no_ptr_gathers": [
        ("    vtag = llc_tag[pbank, pbset, pway]  # [C, W1]",
         "    vtag = tag_rows"),
        ("    vown = llc_owner[pbank, pbset, pway]",
         "    vown = jnp.broadcast_to(arange_c[:, None], tag_rows.shape)"),
        ("    vsh = sharers[pslot, pway * NW + (arange_c[:, None] >> 5)]",
         "    vsh = jnp.zeros(tag_rows.shape, jnp.uint32)"),
    ],
    "no_phase1_validation": [
        ("    weff = jnp.where(\n        (state_rows == I) | (vtag != tag_rows),\n        I,\n        jnp.where(\n            vown == arange_c[:, None],\n            state_rows,\n            jnp.where(vbit, S, I),\n        ),\n    )  # [C, W1] effective MESI per way",
         "    weff = state_rows"),
    ],
    "no_shrows_gather": [
        ("    sh_rows = st.sharers[slot].reshape(C, W2, NW)  # [C, W2, NW]",
         "    sh_rows = jnp.zeros((C, W2, NW), jnp.uint32)"),
    ],
}


def build(name):
    src = SRC
    for old, new in VARIANTS[name]:
        assert old in src, f"{name}: pattern not found: {old[:60]!r}"
        src = src.replace(old, new)
    ns = {
        "__name__": f"primesim_tpu.sim.engine_{name}",
        "__package__": "primesim_tpu.sim",
        "__file__": eng_mod.__file__,
    }
    exec(compile(src, eng_mod.__file__, "exec"), ns)
    return ns["run_chunk"]


def main():
    C = 1024
    cfg = MachineConfig(n_cores=C, n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100, quantum=1000)
    trace = fold_ins(synth.fft_like(C, n_phases=2, points_per_core=16, ins_per_mem=8, seed=42))
    events = jnp.asarray(trace.line_events(cfg.line_bits))
    n = 256
    base = None
    for name in VARIANTS:
        rc = build(name)
        st = init_state(cfg)
        out = rc(cfg, n, events, st)
        np.asarray(out.step)  # sync after warm-up/compile
        t0 = time.perf_counter()
        for _ in range(3):
            out = rc(cfg, n, events, out)
        np.asarray(out.step)  # sync
        dt = (time.perf_counter() - t0) / 3 / n
        if name == "full":
            base = dt
        delta = "" if base is None else f"  (saves {1e3*(base-dt):+.3f})"
        print(f"[{name:22s}] {dt*1e3:.3f} ms/step{delta}", flush=True)


if __name__ == "__main__":
    main()
