"""Bisect per-step cost: stub out pieces of engine.step via source surgery."""
import time

import jax
import jax.numpy as jnp
import numpy as np

import primesim_tpu.sim.engine as eng_mod
from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins

SRC = open(eng_mod.__file__).read()

VARIANTS = {
    "full": [],
    "no_sharers_scatter": [
        ('sharers_n = st.sharers.at[wslot_upd].set(new_row, mode="drop")',
         "sharers_n = st.sharers"),
        ('sharers_n = sharers_n.at[jslot].add(join_row, mode="drop")',
         "sharers_n = sharers_n"),
    ],
    "no_llc_scatter": [
        ('llc_tag_n = st.llc_tag.at[wbank, bset, llc_uway].set(line, mode="drop")',
         "llc_tag_n = st.llc_tag"),
        ('llc_lru_n = st.llc_lru.at[wbank, bset, llc_uway].set(step_no, mode="drop")',
         "llc_lru_n = st.llc_lru"),
        ('llc_owner_n = st.llc_owner.at[wbank, bset, llc_uway].set(new_owner, mode="drop")',
         "llc_owner_n = st.llc_owner"),
        ("llc_lru_n = llc_lru_n.at[\n        jnp.where(join, bank, B), bset, llc_hway\n    ].max(step_no, mode=\"drop\")",
         "llc_lru_n = llc_lru_n"),
    ],
    "no_unpack_CC": [
        ("    sh_bits = unpack_bits(shw)",
         "    sh_bits = jnp.zeros((C, C), bool)"),
        ("    vic_sh_bits = unpack_bits(vic_shw)",
         "    vic_sh_bits = jnp.zeros((C, C), bool)"),
    ],
    "no_CC_reductions": [
        ("    inv_lat = jnp.max(jnp.where(inv_pairs, 2 * pair_lat, 0), axis=1)",
         "    inv_lat = jnp.zeros(C, jnp.int32)"),
        ("    inv_count = jnp.sum(inv_pairs, axis=1).astype(jnp.int32)",
         "    inv_count = jnp.zeros(C, jnp.int32)"),
        ("    inv_hops = jnp.sum(jnp.where(inv_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)",
         "    inv_hops = jnp.zeros(C, jnp.int32)"),
        ("    back_count = jnp.sum(back_pairs, axis=1).astype(jnp.int32)",
         "    back_count = jnp.zeros(C, jnp.int32)"),
        ("    back_hops = jnp.sum(jnp.where(back_pairs, 2 * pair_hops, 0), axis=1).astype(jnp.int32)",
         "    back_hops = jnp.zeros(C, jnp.int32)"),
    ],
    "no_arb_table": [
        ('    table = table.at[jnp.where(req, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
        ('    table = table.at[jnp.where(demoted, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
    ],
    "no_l1_selects": [
        ("    l1_lru = jnp.where(sel_hit, step_no, st.l1_lru)",
         "    l1_lru = st.l1_lru"),
        ("    l1_state = jnp.where(write_hit[:, None] & hitway_sel, M, st.l1_state)",
         "    l1_state = st.l1_state"),
        ("    l1_tag = jnp.where(dup2, -1, l1_tag)", "    l1_tag = l1_tag"),
        ("    l1_state = jnp.where(dup2, I, l1_state)", "    l1_state = l1_state"),
        ("    l1_tag = jnp.where(sel_w, line[:, None], l1_tag)", "    l1_tag = l1_tag"),
        ("    l1_state = jnp.where(sel_w, grant[:, None], l1_state)", "    l1_state = l1_state"),
        ("    l1_lru = jnp.where(sel_w, step_no, l1_lru)", "    l1_lru = l1_lru"),
    ],
    "no_phase1_validation": [
        # effective state = local state (skip directory validation gathers)
        ("    weff = jnp.where(\n        (state_rows == I) | ~whas,\n        I,\n        jnp.where(\n            wowner == arange_c[:, None],\n            state_rows,\n            jnp.where(wshbit, S, I),\n        ),\n    )  # [C, W1] effective MESI per way",
         "    weff = state_rows"),
    ],
}


def build(name):
    src = SRC
    for old, new in VARIANTS[name]:
        assert old in src, f"{name}: pattern not found: {old[:60]!r}"
        src = src.replace(old, new)
    ns = {
        "__name__": f"primesim_tpu.sim.engine_{name}",
        "__package__": "primesim_tpu.sim",
        "__file__": eng_mod.__file__,
    }
    exec(compile(src, eng_mod.__file__, "exec"), ns)
    return ns["run_chunk"]


def main():
    C = 1024
    cfg = MachineConfig(n_cores=C, n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100, quantum=1000)
    trace = fold_ins(synth.fft_like(C, n_phases=2, points_per_core=16, ins_per_mem=8, seed=42))
    events = jnp.asarray(trace.events)
    n = 256
    for name in VARIANTS:
        rc = build(name)
        st = init_state(cfg)
        out = rc(cfg, n, events, st); np.asarray(out.step)
        t0 = time.perf_counter()
        for _ in range(3):
            out = rc(cfg, n, events, out)
        np.asarray(out.step)
        dt = (time.perf_counter() - t0) / 3
        print(f"[{name:22s}] {(dt*1e3-36)/n:.3f} ms/step (call {dt*1e3:.0f}ms)", flush=True)


if __name__ == "__main__":
    main()
