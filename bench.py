"""Benchmark: 1024-core mesh-MESI simulation speed on one TPU chip.

Runs the flagship BASELINE.json ladder config — 1024 in-order cores,
32x32-mesh NoC, private L1s + 1024-bank directory-coherent LLC — over a
SPLASH-2-FFT-shaped synthetic trace (local strided compute phases +
butterfly exchanges), end to end through the chunked Engine (including
host-side counter drains and termination checks).

Prints ONE JSON line: simulated MIPS (million simulated target
instructions per wall second).

`vs_baseline` compares against 20 MIPS — the upper end of the reference
simulator's published multi-host aggregate throughput (ISPASS'14 paper,
SURVEY.md §6; BASELINE.json lists no repo-published numbers), i.e. a
deliberately strong baseline: the whole reference cluster vs one TPU chip.
"""

from __future__ import annotations

import json
import time

BASELINE_MIPS = 20.0


def main() -> None:
    import numpy as np

    from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.trace import synth

    import jax.numpy as jnp

    C = 1024
    CHUNK = 512
    cfg = MachineConfig(
        n_cores=C,
        n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=1000,
        # swept on TPU with upload-synced timing (r4): rl 4 -> 4.27,
        # 6 -> 4.24, 8 -> 4.72, 10 -> 4.20, 12 -> 3.82 MIPS
        local_run_len=8,
    )
    from primesim_tpu.trace.format import fold_ins

    trace = fold_ins(
        synth.fft_like(C, n_phases=4, points_per_core=256, ins_per_mem=8, seed=42)
    )
    n_instructions = trace.total_instructions()

    # compile warm-up of the ACTUAL dispatch path (run_loop), one chunk at
    # the measured shapes; the jit cache persists into the timed run
    from primesim_tpu.sim.engine import run_loop

    warm = Engine(cfg, trace, chunk_steps=CHUNK)
    out = run_loop(
        cfg, CHUNK, warm.events, warm.state, jnp.asarray(1, jnp.int32),
        has_sync=warm.has_sync,  # warm the exact variant the run compiles
    )
    np.asarray(out[0].cycles)  # block

    # best of three timed runs, each synced on its async uploads BEFORE
    # the clock starts (a lazy multi-MB transfer through the remote-TPU
    # tunnel otherwise lands inside the timed dispatch — that, not device
    # compute, was the round-4 "+-30% jitter"); the fastest run is the
    # truer device-rate measurement
    walls = []
    for _ in range(3):
        eng = Engine(cfg, trace, chunk_steps=CHUNK)
        eng.block_until_ready()
        t0 = time.perf_counter()
        eng.run(max_steps=10_000_000)
        walls.append(time.perf_counter() - t0)
    wall = min(walls)

    mips = n_instructions / wall / 1e6
    agg_cycles = int(np.asarray(eng.cycles).max())
    print(
        json.dumps(
            {
                "metric": "simulated_MIPS_1024core_mesh_mesi",
                "value": round(mips, 3),
                "unit": "MIPS",
                "vs_baseline": round(mips / BASELINE_MIPS, 3),
                "detail": {
                    "n_cores": C,
                    "instructions": int(n_instructions),
                    "wall_s": round(wall, 2),
                    "wall_s_runs": [round(w, 2) for w in walls],
                    "steps": eng.steps_run,
                    "max_core_cycles": agg_cycles,
                    "sim_cycles_per_s": round(agg_cycles / wall),
                    "noc_msgs": int(eng.counters["noc_msgs"].sum()),
                    # STATIC RECORD, not part of this run: the round-4
                    # tuning sweeps measured on TPU 2026-07-30 with
                    # upload-synced timing (best-of-2 each), justifying
                    # the rl=8 / chunk=512 defaults above
                    "sweep_mips_static_r4_2026_07_30": {
                        "rl4": 4.265, "rl6": 4.236, "rl8": 4.717,
                        "rl10": 4.195, "rl12": 3.819,
                        "chunk128": 4.775, "chunk256": 4.796,
                        "chunk512": 4.808, "chunk1024": 3.704,
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    main()
