"""Benchmark: 1024-core mesh-MESI simulation speed on one TPU chip.

Runs the flagship BASELINE.json ladder config — 1024 in-order cores,
32x32-mesh NoC, private L1s + 1024-bank directory-coherent LLC — over a
SPLASH-2-FFT-shaped synthetic trace (local strided compute phases +
butterfly exchanges), end to end through the chunked Engine (including
host-side counter drains and termination checks).

Prints ONE JSON line: simulated MIPS (million simulated target
instructions per wall second). The headline metric is the plain
1024-core machine; `extra_metrics.simulated_MIPS_1024core_router_dram`
is the SHIPPED `configs/rung3_1024core_o3.json` machine (hop-by-hop
router contention + DRAM queue + O3 overlap — BASELINE config 3
"NoC-congestion heavy") measured the same way, promoted to a
first-class gated metric since the sort-based FIFO ranking rework
(DESIGN.md §13) put the full-fidelity rung on the perf frontier.

`PRIMETPU_BENCH_SERVE=0` skips the serve_throughput measurement (the
continuous-batching scheduler at sustained 8-slot occupancy vs the
static batch-8 sweep). `PRIMETPU_BENCH_FORK=0` skips the
sweep_fork_speedup measurement (a 16-seed chaos campaign with the
shared prefix forked once vs simulated 16 times, DESIGN.md §16).
`PRIMETPU_BENCH_UNIFIED=0` skips the unified_serve_speedup measurement
(the same job batch through the TCP front-end dispatching to 3 vs 1
real pool workers, DESIGN.md §18). `PRIMETPU_BENCH_SHARD=0` skips the
fleet_shard_scaling measurement (the batch-8 rung-1 fleet sharded over
1/4/8 devices, shard x vmap — DESIGN.md §22; also skipped with a null
metric when fewer than 8 devices are visible — CI pins
XLA_FLAGS=--xla_force_host_platform_device_count=8 for a virtual mesh).
`PRIMETPU_BENCH_COLDSTART=0` skips the cold_start_speedup measurement
(the shipped rung-3 config through two fresh `--exec-cache on`
subprocesses against one cache dir: compile wall bought vs deserialize
wall paid, DESIGN.md §23). `PRIMETPU_BENCH_ATTEST=0` skips the
attest_overhead_pct measurement (the per-chunk fingerprint chain vs
the same chunked dispatch with attest off, DESIGN.md §24; advisory
gate < 3%).

Rung-3 knobs: `PRIMETPU_BENCH_RUNG3=0` skips the rung-3 measurement;
`PRIMETPU_BENCH_RUNG3_FLOOR=<mips>` makes the regression gate HARD
(exit 1 below the floor). Without the env floor the gate is advisory
(recorded in the JSON, never fails the run): absolute MIPS floors are
backend-relative — the 2.0-MIPS acceptance number is a TPU-class bar,
while single-core CPU containers land ~30x lower across the board — so
the auto floor is 2.0 on TPU and 0.15x the same-run headline elsewhere
(rung 3 within ~7x of the fast path proves the O(E log E) ranking holds
regardless of absolute machine speed; pre-rework it sat at ~0.02x).

`vs_baseline` compares against 20 MIPS — the upper end of the reference
simulator's published multi-host aggregate throughput (ISPASS'14 paper,
SURVEY.md §6; BASELINE.json lists no repo-published numbers), i.e. a
deliberately strong baseline: the whole reference cluster vs one TPU chip.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

BASELINE_MIPS = 20.0


def _measure(cfg, trace, chunk: int, runs: int = 3):
    """Best-of-N timed Engine.run with compile warm-up and upload sync
    outside the timed region (the shared measurement protocol)."""
    import numpy as np

    import jax.numpy as jnp

    from primesim_tpu.sim.engine import Engine, run_loop

    warm = Engine(cfg, trace, chunk_steps=chunk)
    tc0 = time.perf_counter()
    out = run_loop(
        cfg, chunk, warm.events, warm.state, jnp.asarray(1, jnp.int32),
        has_sync=warm.has_sync,
    )
    np.asarray(out[0].cycles)  # block until compiled
    compile_wall = time.perf_counter() - tc0
    from primesim_tpu.analysis.recompile import recompile_sentinel

    walls = []
    eng = None
    # the timed loop re-runs the already-compiled program; any compile
    # in here is a jit-key regression AND a corrupted measurement
    with recompile_sentinel(allowed=0, watch=("engine",),
                            label="bench solo timed loop"):
        for _ in range(runs):
            eng = Engine(cfg, trace, chunk_steps=chunk)
            eng.block_until_ready()  # don't bill async uploads
            t0 = time.perf_counter()
            eng.run(max_steps=10_000_000)
            walls.append(time.perf_counter() - t0)
    return eng, min(walls), walls, compile_wall


def _measure_fleet(cfg, traces, chunk: int, runs: int = 2, mesh=None) -> float:
    """Best-of-N timed FleetEngine.run, same warm-up/upload protocol as
    `_measure`: one compiled program batching len(traces) simulations.
    With `mesh` the fleet state is laid out shard x vmap (DESIGN.md §22)."""
    import numpy as np

    import jax.numpy as jnp

    from primesim_tpu.sim.fleet import FleetEngine, fleet_run_loop

    warm = FleetEngine(cfg, traces, chunk_steps=chunk, mesh=mesh)
    out = fleet_run_loop(
        warm.geom_cfg, chunk, warm.events, warm.state,
        jnp.asarray(1, jnp.int32), has_sync=warm.has_sync,
    )
    np.asarray(out[0].cycles)  # block until compiled
    from primesim_tpu.analysis.recompile import recompile_sentinel

    walls = []
    with recompile_sentinel(allowed=0, watch=("fleet",),
                            label="bench fleet timed loop"):
        for _ in range(runs):
            fl = FleetEngine(cfg, traces, chunk_steps=chunk, mesh=mesh)
            fl.block_until_ready()
            t0 = time.perf_counter()
            fl.run(max_steps=10_000_000)
            walls.append(time.perf_counter() - t0)
    return min(walls)


def main() -> None:
    import numpy as np

    from primesim_tpu.config.machine import (
        CacheConfig,
        MachineConfig,
        NocConfig,
    )
    from primesim_tpu.trace import synth
    from primesim_tpu.trace.format import fold_ins

    C = 1024
    CHUNK = int(os.environ.get("PRIMETPU_BENCH_CHUNK", "512"))
    RL = int(os.environ.get("PRIMETPU_BENCH_RL", "8"))
    STEP_IMPL = os.environ.get("PRIMETPU_BENCH_STEP_IMPL", "xla")
    cfg = MachineConfig(
        n_cores=C,
        n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=1000,
        local_run_len=RL,
        step_impl=STEP_IMPL,
    )
    trace = fold_ins(
        synth.fft_like(C, n_phases=4, points_per_core=256, ins_per_mem=8, seed=42)
    )
    n_instructions = trace.total_instructions()

    # the faults-off zero-overhead contract (DESIGN.md §12): the headline
    # number must measure the pre-fault step graph — a config that arms
    # fault injection would silently bench the chaos path instead
    assert not cfg.faults_enabled, "headline bench config must keep faults off"
    eng, wall, walls, compile_wall = _measure(cfg, trace, CHUNK)
    mips = n_instructions / wall / 1e6
    agg_cycles = int(np.asarray(eng.cycles).max())

    # first-class extra metric: the SHIPPED rung-3 config (router NoC +
    # DRAM queue + O3), gated per the docstring. PRIMETPU_BENCH_RUNG3=0
    # skips it (metric and gate report null).
    detail_r3 = None
    r3_gate = None
    if os.environ.get("PRIMETPU_BENCH_RUNG3", "1") != "0":
        r3_path = os.path.join(os.path.dirname(__file__), "configs",
                               "rung3_1024core_o3.json")
        with open(r3_path) as f:
            cfg3 = MachineConfig.from_json(f.read())
        if STEP_IMPL != "xla":
            cfg3 = dataclasses.replace(cfg3, step_impl=STEP_IMPL)
        eng3, wall3, _, _ = _measure(cfg3, trace, CHUNK, runs=2)
        mips3 = round(n_instructions / wall3 / 1e6, 3)
        detail_r3 = {
            "config": "configs/rung3_1024core_o3.json",
            "contention_model": cfg3.noc.contention_model,
            "dram_queue": cfg3.dram_queue,
            "mips": mips3,
            "wall_s": round(wall3, 2),
            "noc_contention_cycles": int(
                eng3.counters["noc_contention_cycles"].sum()
            ),
            "dram_queue_cycles": int(eng3.counters["dram_queue_cycles"].sum()),
        }
        floor_env = os.environ.get("PRIMETPU_BENCH_RUNG3_FLOOR")
        if floor_env is not None:
            floor, hard = float(floor_env), True
        else:
            import jax

            on_tpu = jax.default_backend() == "tpu"
            floor = 2.0 if on_tpu else round(0.15 * mips, 3)
            hard = False
        r3_gate = {
            "floor_mips": floor,
            "hard": hard,
            "passed": bool(mips3 >= floor),
        }

    # fleet scaling: aggregate MIPS batching B independent simulations
    # through ONE compiled program (sim.fleet) on the rung-1/64-core
    # config. The ~2.8 ms/step floor is serial kernel-chain depth, not
    # bytes, so on TPU the aggregate should scale well toward B=8; on CPU
    # this records the shape without gating it.
    r1_path = os.path.join(os.path.dirname(__file__), "configs",
                           "rung1_64core_fft.json")
    with open(r1_path) as f:
        cfg1 = MachineConfig.from_json(f.read())
    fleet_traces = [
        fold_ins(
            synth.fft_like(
                cfg1.n_cores, n_phases=2, points_per_core=128,
                ins_per_mem=8, seed=52 + b,
            )
        )
        for b in range(8)
    ]
    fleet_scaling = {}
    for bsz in (1, 4, 8):
        trs = fleet_traces[:bsz]
        total_ins = sum(t.total_instructions() for t in trs)
        wall_b = _measure_fleet(cfg1, trs, CHUNK)
        fleet_scaling[str(bsz)] = round(total_ins / wall_b / 1e6, 3)

    # fleet shard scaling: the batch-8 fleet above with its state laid
    # out over 1/4/8 devices (shard x vmap, DESIGN.md §22) — aggregate
    # MIPS per mesh size. On the CI virtual CPU mesh the devices share
    # one socket, so the floor is advisory (non-decreasing 1 -> 8 is the
    # shape a real pod should show); it records pass/fail but never
    # fails the run. PRIMETPU_BENCH_SHARD=0 skips (metric reports null),
    # as does a host with fewer than 8 visible devices.
    fleet_shard_scaling = None
    fleet_shard_gate = None
    if os.environ.get("PRIMETPU_BENCH_SHARD", "1") != "0":
        import jax

        if len(jax.devices()) >= 8:
            from primesim_tpu.parallel.sharding import tile_mesh

            total_ins = sum(t.total_instructions() for t in fleet_traces)
            fleet_shard_scaling = {}
            for nd in (1, 4, 8):
                wall_d = _measure_fleet(
                    cfg1, fleet_traces, CHUNK, mesh=tile_mesh(nd))
                fleet_shard_scaling[str(nd)] = round(
                    total_ins / wall_d / 1e6, 3)
            fleet_shard_gate = {
                "floor": "MIPS(1) <= MIPS(4) <= MIPS(8)",
                "hard": False,
                "passed": bool(
                    fleet_shard_scaling["1"] <= fleet_shard_scaling["4"]
                    <= fleet_shard_scaling["8"]
                ),
            }

    # serve throughput: the continuous-batching scheduler (serve/) kept
    # at sustained 8-slot occupancy on the same rung-1 config/workload as
    # fleet_scaling — jobs/min and aggregate MIPS, with the static
    # batch-8 sweep number alongside as the ceiling (the gap is
    # splice/harvest/journal overhead + partial-occupancy drain at the
    # tail). PRIMETPU_BENCH_SERVE=0 skips (metric reports null).
    serve_detail = None
    if os.environ.get("PRIMETPU_BENCH_SERVE", "1") != "0":
        import tempfile

        from primesim_tpu.serve import Job, JobJournal, Scheduler
        from primesim_tpu.serve.scheduler import PAGE_EVENTS

        synth_spec = (
            "fft_like:n_phases=2,points_per_core=128,ins_per_mem=8,seed={}"
        )
        cap_pages = -(-max(t.max_len for t in fleet_traces) // PAGE_EVENTS)
        n_jobs = 16
        with tempfile.TemporaryDirectory() as td:
            sched = Scheduler(
                cfg1, JobJournal(td), td, buckets=((8, cap_pages),),
                chunk_steps=CHUNK, max_queue=n_jobs + 1,
                checkpoint_every_s=1e9,  # measure serving, not snapshots
            )
            warm = Job(job_id="warm", synth=synth_spec.format(51))
            sched.submit(warm)
            while not warm.terminal:
                sched.tick()
            jobs = [
                Job(job_id=f"b{i:03d}", synth=synth_spec.format(60 + i))
                for i in range(n_jobs)
            ]
            t0 = time.perf_counter()
            for j in jobs:
                sched.submit(j)
            while not all(j.terminal for j in jobs):
                sched.tick()
            wall_srv = time.perf_counter() - t0
            sched.journal.close()
        served_ins = sum(
            j.result["instructions"] for j in jobs if j.result
        )
        serve_detail = {
            "jobs": n_jobs,
            "slots": 8,
            "jobs_per_min": round(n_jobs / wall_srv * 60.0, 2),
            "aggregate_mips": round(served_ins / wall_srv / 1e6, 3),
            "static_fleet8_mips": fleet_scaling["8"],
            "states": sorted({j.state for j in jobs}),
            "wall_s": round(wall_srv, 2),
        }

    # prefix-fork speedup (DESIGN.md §16): a 16-seed chaos campaign on
    # the rung-1 config with one late scheduled link-degrade. Every
    # element shares the trace and the full timing-knob vector and can
    # only diverge at the fault-schedule start, so the forked path
    # simulates the shared prefix ONCE (solo Engine) and broadcasts the
    # snapshot into all 16 fleet slots; the unforked fleet pays for that
    # prefix 16 times. Wall-clock gate is advisory at 2.0x (never hard —
    # the ratio depends on backend batching economics, see
    # fleet_scaling). PRIMETPU_BENCH_FORK=0 skips (metric reports null).
    fork_detail = None
    fork_gate = None
    if os.environ.get("PRIMETPU_BENCH_FORK", "1") != "0":
        from primesim_tpu.config.machine import FAULT_LINK_DEGRADE
        from primesim_tpu.sim.engine import Engine
        from primesim_tpu.sim.fleet import FleetEngine
        from primesim_tpu.sim.prefix import execute_prefix_plan, plan_prefix

        B_FORK = 16
        # fork granularity is chunk_steps: the run must span several
        # chunks so a chunk-floored 3/4 fork point leaves a real tail —
        # the headline CHUNK (512) would swallow this trace whole
        FCHUNK = min(CHUNK, 128)
        fork_trace = fold_ins(
            synth.fft_like(
                cfg1.n_cores, n_phases=4, points_per_core=256,
                ins_per_mem=8, seed=97,
            )
        )
        # place the scheduled event at ~3/4 of the run so the shared
        # prefix dominates but every element still runs a real tail
        probe = Engine(cfg1, fork_trace, chunk_steps=FCHUNK)
        probe.run(max_steps=10_000_000)
        ev_step = max(
            FCHUNK, int(probe.steps_run) * 3 // 4 // FCHUNK * FCHUNK
        )
        cfg_fork = dataclasses.replace(
            cfg1, faults_enabled=True, max_fault_events=1,
            fault_events=((ev_step, FAULT_LINK_DEGRADE, 0, 4),),
        )
        fork_ovs = [{"fault_seed": 700 + b} for b in range(B_FORK)]
        fork_traces = [fork_trace] * B_FORK

        def _campaign(forked: bool):
            fl = FleetEngine(
                cfg_fork, fork_traces, fork_ovs, chunk_steps=FCHUNK
            )
            fl.block_until_ready()
            t0 = time.perf_counter()
            pre = 0
            if forked:
                groups = plan_prefix(
                    fl.elem_cfgs, fl.traces, mode="auto",
                    chunk_steps=FCHUNK, cap=10_000_000,
                )
                pre = execute_prefix_plan(fl, groups)["prefix_steps"]
            fl.run(max_steps=10_000_000)
            return time.perf_counter() - t0, pre

        _campaign(False)  # compile the fleet program
        _campaign(True)  # compile the solo prefix program
        from primesim_tpu.analysis.recompile import recompile_sentinel

        with recompile_sentinel(allowed=0, label="bench fork campaign"):
            wall_unforked = min(_campaign(False)[0] for _ in range(2))
            forked_runs = [_campaign(True) for _ in range(2)]
        wall_forked = min(w for w, _ in forked_runs)
        fork_speedup = wall_unforked / wall_forked
        fork_detail = {
            "elements": B_FORK,
            "divergence_step": int(ev_step),
            "prefix_steps": int(forked_runs[0][1]),
            "wall_s_unforked": round(wall_unforked, 3),
            "wall_s_forked": round(wall_forked, 3),
            "speedup_x": round(fork_speedup, 3),
        }
        fork_gate = {
            "floor_x": 2.0,
            "hard": False,
            "passed": bool(fork_speedup >= 2.0),
        }

    # telemetry overhead (DESIGN.md §15 overhead contract): wall time of
    # the chunked engine with the --obs basic metric ring attached vs the
    # identical chunked dispatch with obs off, on the headline machine
    # with a shorter trace at chunk 64 (enough chunks that the per-chunk
    # host hook dominates the comparison, not dispatch noise). Advisory:
    # recorded + gated at < 3%, never fails the run (host-timer noise on
    # shared CI runners makes a hard wall-clock gate flaky by design).
    # PRIMETPU_BENCH_OBS=0 skips (metric and gate report null).
    obs_detail = None
    obs_gate = None
    if os.environ.get("PRIMETPU_BENCH_OBS", "1") != "0":
        from primesim_tpu.obs import Recorder
        from primesim_tpu.sim.engine import Engine, run_chunk

        OBS_CHUNK = 64
        obs_trace = fold_ins(
            synth.fft_like(
                C, n_phases=2, points_per_core=64, ins_per_mem=8, seed=42
            )
        )
        warm_o = Engine(cfg, obs_trace, chunk_steps=OBS_CHUNK)
        out_o = run_chunk(
            cfg, OBS_CHUNK, warm_o.events, warm_o.state,
            has_sync=warm_o.has_sync,
        )
        np.asarray(out_o.cycles)  # block until compiled

        def _chunked_wall(make_rec, runs: int = 3):
            best, chunks = None, 0
            for _ in range(runs):
                e = Engine(cfg, obs_trace, chunk_steps=OBS_CHUNK)
                rec = make_rec()
                if rec is not None:
                    rec.attach(e)
                e.block_until_ready()
                t0 = time.perf_counter()
                e.run_chunked(max_steps=10_000_000)
                w = time.perf_counter() - t0
                best = w if best is None else min(best, w)
                chunks = e.steps_run // OBS_CHUNK
            return best, chunks

        wall_off, n_chunks = _chunked_wall(lambda: None)
        wall_basic, _ = _chunked_wall(lambda: Recorder("basic"))
        obs_overhead_pct = (wall_basic - wall_off) / wall_off * 100.0
        obs_detail = {
            "chunks": int(n_chunks),
            "chunk_steps": OBS_CHUNK,
            "wall_s_obs_off": round(wall_off, 4),
            "wall_s_obs_basic": round(wall_basic, 4),
            "overhead_pct": round(obs_overhead_pct, 2),
        }
        obs_gate = {
            "floor_pct": 3.0,
            "hard": False,
            "passed": bool(obs_overhead_pct < 3.0),
        }

    # result-integrity contract (DESIGN.md §24): the per-chunk sha256
    # fingerprint chain vs the identical chunked dispatch with attest
    # off — the chain hashes host values the drain already transferred,
    # so the cost is one digest per committed chunk. Advisory at < 3%
    # like obs (host-timer noise on shared runners). PRIMETPU_BENCH_ATTEST=0
    # skips (metric and gate report null).
    attest_detail = None
    attest_gate = None
    if os.environ.get("PRIMETPU_BENCH_ATTEST", "1") != "0":
        from primesim_tpu.attest import SoloAttest
        from primesim_tpu.sim.engine import Engine, run_chunk

        AT_CHUNK = 64
        at_trace = fold_ins(
            synth.fft_like(
                C, n_phases=2, points_per_core=64, ins_per_mem=8, seed=43
            )
        )
        warm_a = Engine(cfg, at_trace, chunk_steps=AT_CHUNK)
        out_a = run_chunk(
            cfg, AT_CHUNK, warm_a.events, warm_a.state,
            has_sync=warm_a.has_sync,
        )
        np.asarray(out_a.cycles)  # block until compiled

        def _attest_wall(on: bool, runs: int = 3):
            best, chunks, head = None, 0, None
            for _ in range(runs):
                e = Engine(cfg, at_trace, chunk_steps=AT_CHUNK)
                if on:
                    e.attest = SoloAttest(AT_CHUNK)
                e.block_until_ready()
                t0 = time.perf_counter()
                e.run_chunked(max_steps=10_000_000)
                w = time.perf_counter() - t0
                best = w if best is None else min(best, w)
                chunks = e.steps_run // AT_CHUNK
                if on:
                    head = e.attest.payload()["head"]
            return best, chunks, head

        wall_plain, at_chunks, _ = _attest_wall(False)
        wall_chain, _, at_head = _attest_wall(True)
        attest_overhead_pct = (wall_chain - wall_plain) / wall_plain * 100.0
        attest_detail = {
            "chunks": int(at_chunks),
            "chunk_steps": AT_CHUNK,
            "wall_s_attest_off": round(wall_plain, 4),
            "wall_s_attest_chain": round(wall_chain, 4),
            "chain_head": at_head,
            "overhead_pct": round(attest_overhead_pct, 2),
        }
        attest_gate = {
            "floor_pct": 3.0,
            "hard": False,
            "passed": bool(attest_overhead_pct < 3.0),
        }

    # calibration economics (DESIGN.md §25): a full `primetpu calibrate`
    # self-test fit — synthesize observed values at known truth knobs,
    # then pattern-search two knobs back from the config defaults. Every
    # fleet dispatch shares ONE compiled program (constant candidate x
    # entry batch), so the wall clock prices compile-once + N cache-hit
    # dispatches. Advisory gate: the fit must actually recover the truth
    # (cost ~ 0). PRIMETPU_BENCH_CALIB=0 skips (metric and gate null).
    calib_detail = None
    calib_gate = None
    if os.environ.get("PRIMETPU_BENCH_CALIB", "1") != "0":
        from primesim_tpu.calib.fit import fit as calib_fit
        from primesim_tpu.calib.fit import synthesize_observed
        from primesim_tpu.calib.table import CalibEntry, CalibTable
        from primesim_tpu.config.machine import small_test_config

        ccfg = small_test_config(8, n_banks=4, quantum=500)
        ctable = CalibTable(
            name="bench_selftest",
            entries=(
                CalibEntry("chase", "pointer_chase",
                           {"n_mem_ops": 48, "n_nodes": 16},
                           "cycles_per_mem_op", 1.0),
                CalibEntry("xchg", "uniform_random",
                           {"n_mem_ops": 48, "shared_frac": 1, "seed": 1},
                           "cycles_per_mem_op", 1.0),
            ),
        )
        truth = {"llc_lat": 16, "dram_lat": 151}
        ctable = synthesize_observed(ccfg, ctable, truth, chunk_steps=64)
        t0 = time.perf_counter()
        cres = calib_fit(ccfg, ctable, fit_keys=tuple(truth),
                         chunk_steps=64)
        calib_wall = time.perf_counter() - t0
        calib_detail = {
            "fit_keys": sorted(truth),
            "truth": truth,
            "knobs": cres.knobs,
            "cost": cres.cost,
            "rounds": cres.rounds,
            "fleet_runs": cres.fleet_runs,
            "batch": cres.batch,
            "wall_s": round(calib_wall, 2),
            "wall_ms_per_dispatch": round(
                calib_wall * 1000.0 / max(1, cres.fleet_runs), 1
            ),
        }
        calib_gate = {
            "max_cost": 1e-6,
            "hard": False,
            "passed": bool(cres.cost <= 1e-6),
        }

    # degraded-mode recovery economics (DESIGN.md §26): a supervised
    # sharded run that loses a device at a chunk boundary finishes
    # bit-exact after the reshard rung; this prices the recovery —
    # snapshot reload + re-placement onto the smaller mesh + recompile —
    # against the identical run with no loss. Advisory only (the cost
    # is dominated by XLA recompile wall, which varies wildly across
    # hosts); null when PRIMETPU_BENCH_DEGRADE=0 or < 2 visible devices.
    degrade_detail = None
    if os.environ.get("PRIMETPU_BENCH_DEGRADE", "1") != "0":
        import tempfile

        import jax

        from primesim_tpu.chaos import plan as CP
        from primesim_tpu.chaos import sites as CS
        from primesim_tpu.config.machine import small_test_config
        from primesim_tpu.parallel import sharding
        from primesim_tpu.sim.engine import Engine
        from primesim_tpu.sim.supervisor import RunSupervisor

        if len(jax.devices()) >= 2:
            dcfg = small_test_config(8, n_banks=8)
            dtrace = synth.fft_like(
                8, n_phases=1, points_per_core=32, seed=9
            )
            dn = sharding.largest_valid_submesh(dcfg, len(jax.devices()))

            def _degrade_run(with_loss: bool):
                sharding.restore_devices()
                snap = tempfile.mkdtemp(prefix="primetpu-bench-degrade-")
                mesh = sharding.tile_mesh(devices=jax.devices()[:dn])
                eng = Engine(dcfg, dtrace, chunk_steps=64, mesh=mesh)
                sup = RunSupervisor(
                    eng, snapshot_dir=snap, checkpoint_every_chunks=1,
                    handle_signals=False,
                )
                if with_loss:
                    CS.install(CP.FaultPlan(seed=0, events=(
                        CP.FaultEvent(
                            site="devices.revoke", occurrence=2,
                            action="revoke", args=(("n", 1),),
                        ),
                    )))
                t0 = time.perf_counter()
                try:
                    sup.run()
                finally:
                    CS.deactivate()
                    sharding.restore_devices()
                return time.perf_counter() - t0, list(sup.degrade_rungs)

            degrade_wall_clean, _ = _degrade_run(False)
            degrade_wall_loss, degrade_rungs = _degrade_run(True)
            degrade_detail = {
                "devices": int(dn),
                "wall_s_clean": round(degrade_wall_clean, 3),
                "wall_s_with_device_loss": round(degrade_wall_loss, 3),
                "degrade_recovery_wall_s": round(
                    degrade_wall_loss - degrade_wall_clean, 3
                ),
                "rungs": degrade_rungs,
            }

    # LIVE per-phase cuts (scripts/prof/prof_phase.py source surgery) on
    # elastic pool scaling (DESIGN.md §17): the same 16-element campaign
    # through `sweep --workers 1` vs `--workers 3` — real worker
    # processes over the unix socket, so the measurement prices the
    # whole protocol (lease RPCs, heartbeats, per-chunk checkpoint
    # fsyncs, per-worker JIT compile) against the parallelism it buys.
    # Advisory at 1.5x (never hard: the ratio collapses on starved CI
    # runners where 3 workers share 2 cores). PRIMETPU_BENCH_POOL=0
    # skips (metric reports null).
    pool_detail = None
    pool_gate = None
    if os.environ.get("PRIMETPU_BENCH_POOL", "1") != "0":
        import subprocess
        import tempfile

        from primesim_tpu.config.machine import small_test_config

        pool_tmp = tempfile.mkdtemp(prefix="primetpu-bench-pool-")
        pool_cfg_path = os.path.join(pool_tmp, "cfg.json")
        with open(pool_cfg_path, "w") as f:
            f.write(small_test_config(4).to_json())
        pool_cmd = [
            sys.executable, "-m", "primesim_tpu.cli", "sweep",
            pool_cfg_path, "--synth",
            "fft_like:n_phases=2,points_per_core=64,ins_per_mem=4,seed=5",
            "--chunk-steps", "64",
        ]
        for i in range(16):
            pool_cmd += ["--vary", f"llc_lat={8 + i}"]

        def _pool_campaign(workers: int) -> float:
            t0 = time.perf_counter()
            subprocess.run(
                pool_cmd + ["--workers", str(workers)],
                check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            return time.perf_counter() - t0

        pool_wall_1 = _pool_campaign(1)
        pool_wall_3 = _pool_campaign(3)
        pool_speedup = pool_wall_1 / pool_wall_3
        pool_detail = {
            "elements": 16,
            "wall_s_workers1": round(pool_wall_1, 3),
            "wall_s_workers3": round(pool_wall_3, 3),
            "speedup_x": round(pool_speedup, 3),
        }
        pool_gate = {
            "floor_x": 1.5,
            "hard": False,
            "passed": bool(pool_speedup >= 1.5),
        }

    # unified elastic serving economics (DESIGN.md §18): the same job
    # batch submitted to the TCP front-end, dispatched to an autoscaled
    # fleet of 3 vs 1 real pool-worker processes — front-end, coordinator
    # and workers all real processes, so the measurement prices the whole
    # unified stack (admission journal fsyncs, enqueue/collect RPCs,
    # lease protocol, per-worker JIT compile) against the parallelism.
    # Advisory at 2.0x (never hard: collapses on starved CI runners).
    # PRIMETPU_BENCH_UNIFIED=0 skips (metric reports null).
    unified_detail = None
    unified_gate = None
    if os.environ.get("PRIMETPU_BENCH_UNIFIED", "1") != "0":
        import re as _re
        import subprocess
        import tempfile

        from primesim_tpu.config.machine import small_test_config
        from primesim_tpu.serve.client import ServeClient

        uni_tmp = tempfile.mkdtemp(prefix="primetpu-bench-unified-")
        uni_cfg_path = os.path.join(uni_tmp, "cfg.json")
        with open(uni_cfg_path, "w") as f:
            f.write(small_test_config(4).to_json())
        UNI_JOBS = 12

        def _unified_campaign(workers: int) -> float:
            sdir = os.path.join(uni_tmp, f"w{workers}")
            os.makedirs(sdir, exist_ok=True)
            err_path = os.path.join(sdir, "serve.log")
            srv = subprocess.Popen(
                [sys.executable, "-m", "primesim_tpu.cli", "serve",
                 uni_cfg_path,
                 "--state-dir", os.path.join(sdir, "state"),
                 "--tcp", "127.0.0.1:0",
                 "--pool-dir", os.path.join(sdir, "pool"),
                 "--workers", str(workers), "--chunk-steps", "64"],
                stdout=subprocess.DEVNULL, stderr=open(err_path, "w"),
            )
            try:
                target = None
                for _ in range(1800):
                    m = _re.search(r"serve: listening on (\S+)",
                                   open(err_path).read())
                    if m:
                        target = m.group(1)
                        break
                    if srv.poll() is not None:
                        raise RuntimeError(
                            "front-end died: "
                            + open(err_path).read()[-500:]
                        )
                    time.sleep(0.1)
                cli = ServeClient(target, timeout_s=60.0)
                t0 = time.perf_counter()
                ids = [
                    cli.submit(
                        synth=f"stream:n_mem_ops=400,seed={i}",
                        client=f"bench{i % 2}",
                    )["job_id"]
                    for i in range(UNI_JOBS)
                ]
                for jid in ids:
                    job = cli.wait(jid, timeout_s=900.0)
                    assert job["state"] == "DONE", job
                wall = time.perf_counter() - t0
                cli.drain()
                srv.wait(timeout=120)
                return wall
            finally:
                if srv.poll() is None:
                    srv.kill()

        uni_wall_1 = _unified_campaign(1)
        uni_wall_3 = _unified_campaign(3)
        uni_speedup = uni_wall_1 / uni_wall_3
        unified_detail = {
            "jobs": UNI_JOBS,
            "wall_s_workers1": round(uni_wall_1, 3),
            "wall_s_workers3": round(uni_wall_3, 3),
            "speedup_x": round(uni_speedup, 3),
        }
        unified_gate = {
            "floor_x": 2.0,
            "hard": False,
            "passed": bool(uni_speedup >= 2.0),
        }

    # cold-start economics (DESIGN.md §23): the SHIPPED rung-3 config
    # through two fresh `primetpu run --exec-cache on` subprocesses
    # against one empty cache dir — run 1 pays XLA compilation and
    # persists the executables, run 2 deserializes them. The speedup is
    # compile wall bought vs deserialize wall paid; time-to-first-step
    # rides alongside (it additionally carries trace synthesis + device
    # upload, which the cache does not touch). Advisory at 5.0x (the
    # acceptance bar; absolute compile walls are backend- and
    # core-count-relative). PRIMETPU_BENCH_COLDSTART=0 skips (metric
    # reports null).
    cold_detail = None
    cold_gate = None
    if os.environ.get("PRIMETPU_BENCH_COLDSTART", "1") != "0":
        import shutil
        import subprocess
        import tempfile

        cs_cache = tempfile.mkdtemp(prefix="primetpu-bench-exec-")
        cs_cmd = [
            sys.executable, "-m", "primesim_tpu.cli", "run",
            os.path.join(os.path.dirname(__file__), "configs",
                         "rung3_1024core_o3.json"),
            "--synth", "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4",
            "--fold", "--max-steps", "64", "--chunk-steps", "32",
            "--exec-cache", "on",
        ]

        def _fresh_process_run() -> dict:
            env = dict(os.environ, PRIMETPU_CACHE_DIR=cs_cache)
            out = subprocess.run(
                cs_cmd, check=True, capture_output=True, text=True, env=env
            ).stdout
            metrics = {}
            for line in out.splitlines():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "metric" in rec:
                    metrics[rec["metric"]] = rec
            return metrics

        try:
            cold_m = _fresh_process_run()   # empty dir: compile + persist
            warm_m = _fresh_process_run()   # same dir: deserialize
            cold_ec = cold_m["exec_cache"]["detail"]
            warm_ec = warm_m["exec_cache"]["detail"]
            cold_compile = float(cold_ec["compile_wall_s"])
            warm_paid = (float(warm_ec["compile_wall_s"])
                         + float(warm_ec["load_wall_s"]))
            cs_speedup = cold_compile / max(warm_paid, 1e-9)
            cold_detail = {
                "config": "configs/rung3_1024core_o3.json",
                "cold_ttfs_s": cold_m["time_to_first_step"]["value"],
                "warm_ttfs_s": warm_m["time_to_first_step"]["value"],
                "cold_compile_wall_s": round(cold_compile, 3),
                "warm_load_wall_s": round(
                    float(warm_ec["load_wall_s"]), 3),
                "warm_hits": int(warm_ec["hits"]),
                "warm_misses": int(warm_ec["misses"]),
                "speedup_x": round(cs_speedup, 3),
            }
            cold_gate = {
                "floor_x": 5.0,
                "hard": False,
                "passed": bool(cs_speedup >= 5.0
                               and warm_ec["misses"] == 0),
            }
        finally:
            shutil.rmtree(cs_cache, ignore_errors=True)

    # the headline machine: cumulative ms/step at each phase marker, so
    # every bench artifact carries the serial-chain decomposition next to
    # the static r5 record. PRIMETPU_BENCH_PHASE_CUTS=0 skips (each cut
    # recompiles the truncated step — ~10 extra compiles).
    phase_ms = None
    if os.environ.get("PRIMETPU_BENCH_PHASE_CUTS", "1") != "0":
        import importlib.util

        pp_path = os.path.join(
            os.path.dirname(__file__), "scripts", "prof", "prof_phase.py"
        )
        spec = importlib.util.spec_from_file_location("prof_phase", pp_path)
        pp = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pp)
        cut_trace = fold_ins(
            synth.fft_like(
                C, n_phases=2, points_per_core=16, ins_per_mem=8, seed=42
            )
        )
        phase_ms = {
            k: round(v, 3)
            for k, v in pp.phase_cuts(
                cfg, cut_trace, n_steps=64, repeats=2
            ).items()
        }

    print(
        json.dumps(
            {
                "metric": "simulated_MIPS_1024core_mesh_mesi",
                "value": round(mips, 3),
                "unit": "MIPS",
                "vs_baseline": round(mips / BASELINE_MIPS, 3),
                # the full-fidelity ladder rung as its own gated metric
                # (null when PRIMETPU_BENCH_RUNG3=0 skipped the run)
                "extra_metrics": {
                    "simulated_MIPS_1024core_router_dram": (
                        detail_r3["mips"] if detail_r3 else None
                    ),
                    # --obs basic wall-clock cost over the same chunked
                    # dispatch with obs off (null when
                    # PRIMETPU_BENCH_OBS=0; advisory gate < 3%)
                    "obs_overhead_pct": (
                        obs_detail["overhead_pct"] if obs_detail else None
                    ),
                    # 16-seed chaos campaign forked at the fault-schedule
                    # start vs unforked (null when PRIMETPU_BENCH_FORK=0;
                    # advisory gate >= 2.0x)
                    "sweep_fork_speedup": (
                        fork_detail["speedup_x"] if fork_detail else None
                    ),
                    # the same campaign through 1 vs 3 real worker
                    # processes (null when PRIMETPU_BENCH_POOL=0;
                    # advisory gate >= 1.5x)
                    "pool_sweep_speedup": (
                        pool_detail["speedup_x"] if pool_detail else None
                    ),
                    # the same job batch through the unified TCP
                    # front-end at 3 vs 1 pool workers (null when
                    # PRIMETPU_BENCH_UNIFIED=0; advisory gate >= 2.0x)
                    "unified_serve_speedup": (
                        unified_detail["speedup_x"]
                        if unified_detail else None
                    ),
                    # rung-3 compile wall bought by the AOT executable
                    # cache across fresh processes (null when
                    # PRIMETPU_BENCH_COLDSTART=0; advisory gate >= 5.0x)
                    "cold_start_speedup": (
                        cold_detail["speedup_x"] if cold_detail else None
                    ),
                    # per-chunk fingerprint-chain wall cost over the
                    # same chunked dispatch with attest off (null when
                    # PRIMETPU_BENCH_ATTEST=0; advisory gate < 3%)
                    "attest_overhead_pct": (
                        attest_detail["overhead_pct"]
                        if attest_detail else None
                    ),
                    # wall clock of a full 2-knob calibrate self-test
                    # fit over one compiled fleet (null when
                    # PRIMETPU_BENCH_CALIB=0; advisory gate: truth
                    # recovered with ~zero residual)
                    "calibrate_sweep_wall_s": (
                        calib_detail["wall_s"] if calib_detail else None
                    ),
                },
                "detail": {
                    "n_cores": C,
                    "instructions": int(n_instructions),
                    "wall_s": round(wall, 2),
                    "wall_s_runs": [round(w, 2) for w in walls],
                    # compile/run wall split (DESIGN.md §23): the one-off
                    # trace+lower+compile wall the warm-up paid vs the
                    # steady-state run wall the timed loop measures
                    "compile_wall_s": round(compile_wall, 2),
                    "run_wall_s": round(wall, 2),
                    "steps": eng.steps_run,
                    "max_core_cycles": agg_cycles,
                    "sim_cycles_per_s": round(agg_cycles / wall),
                    "noc_msgs": int(eng.counters["noc_msgs"].sum()),
                    "local_run_len": RL,
                    "chunk_steps": CHUNK,
                    "step_impl": STEP_IMPL,
                    # asserted off above: the headline measures the
                    # pre-fault step graph (DESIGN.md §12 zero-overhead
                    # contract)
                    "faults_enabled": cfg.faults_enabled,
                    # live cumulative phase cuts on THIS machine/backend
                    # (None when PRIMETPU_BENCH_PHASE_CUTS=0)
                    "phase_ms_cuts_measured": phase_ms,
                    "rung3_shipped_config": detail_r3,
                    "rung3_regression_gate": r3_gate,
                    # telemetry overhead contract (DESIGN.md §15): the
                    # metric ring at --obs basic vs obs off on the same
                    # chunked dispatch (null when PRIMETPU_BENCH_OBS=0)
                    "obs_overhead": obs_detail,
                    "obs_overhead_gate": obs_gate,
                    # result-integrity overhead contract (DESIGN.md
                    # §24): the fingerprint chain at --attest chain vs
                    # attest off on the same chunked dispatch (null
                    # when PRIMETPU_BENCH_ATTEST=0)
                    "attest_overhead": attest_detail,
                    "attest_overhead_gate": attest_gate,
                    # calibration economics (DESIGN.md §25): self-test
                    # fit wall over one compiled constant-shape fleet
                    # (null when PRIMETPU_BENCH_CALIB=0)
                    "calibrate_sweep": calib_detail,
                    "calibrate_sweep_gate": calib_gate,
                    # aggregate MIPS batching B sims through one program
                    # (rung-1/64-core config, one distinct trace per
                    # element)
                    "fleet_scaling": fleet_scaling,
                    # the batch-8 fleet sharded over 1/4/8 devices
                    # (shard x vmap, DESIGN.md §22); advisory floor,
                    # null when PRIMETPU_BENCH_SHARD=0 or < 8 devices
                    "fleet_shard_scaling": fleet_shard_scaling,
                    "fleet_shard_scaling_gate": fleet_shard_gate,
                    # continuous-batching service throughput at sustained
                    # 8-slot occupancy (null when PRIMETPU_BENCH_SERVE=0)
                    "serve_throughput": serve_detail,
                    # prefix-fork campaign economics (DESIGN.md §16):
                    # shared prefix simulated once vs 16 times (null when
                    # PRIMETPU_BENCH_FORK=0)
                    "sweep_fork": fork_detail,
                    "sweep_fork_gate": fork_gate,
                    # elastic pool campaign economics (DESIGN.md §17):
                    # 16 units through 1 vs 3 worker processes (null
                    # when PRIMETPU_BENCH_POOL=0)
                    "pool_sweep": pool_detail,
                    "pool_sweep_gate": pool_gate,
                    # unified elastic serving (DESIGN.md §18): the same
                    # job batch through the TCP front-end at 3 vs 1
                    # workers (null when PRIMETPU_BENCH_UNIFIED=0)
                    "unified_serve": unified_detail,
                    "unified_serve_gate": unified_gate,
                    # cold-start economics (DESIGN.md §23): two fresh
                    # rung-3 processes vs one exec-cache dir (null when
                    # PRIMETPU_BENCH_COLDSTART=0)
                    "cold_start": cold_detail,
                    "cold_start_gate": cold_gate,
                    # device-loss recovery cost on a sharded supervised
                    # run (DESIGN.md §26); advisory, null when
                    # PRIMETPU_BENCH_DEGRADE=0 or < 2 visible devices
                    "degrade_recovery": degrade_detail,
                    # STATIC RECORD: round-5 restructure evidence measured
                    # on TPU 2026-07-30 (scripts/prof/prof_phase.py
                    # cumulative cuts / prof_bisect.py ablations,
                    # flagship shapes, rl=8).
                    # Per-KERNEL overhead dominates this workload; the
                    # remaining floor is the step's serial kernel chain.
                    "perf_evidence_static_r5": {
                        "phase_ms_cuts_rl8": {
                            "quantum": 0.09, "local_runs": 0.16,
                            "probe+classify": 0.8, "arb+inv+lat": 0.3,
                            "scatters+tail": 1.0,
                        },
                        "landed": {
                            "closed_form_local_runs_ms": 0.7,
                            "fused_l1_single_scatter": True,
                            "fused_dirm_row": True,
                            "batched_counter_adds_ms": 0.2,
                            "llc_meta_128pad_vs_transposed_ms": 0.35,
                        },
                        "rejected_measured_slower": {
                            "windowed_dynamic_col_gathers_ms": 5.6,
                            "chained_scatter_same_array_ms": 5.0,
                            "phase1_prefetch_reuse_selects": 0.9,
                            "scan_unroll2_gain_ms": 0.14,
                        },
                        "sweeps_final_mips": {
                            "rl6": 4.56, "rl8": 4.62, "rl10": 4.14,
                            "rl12": 3.71, "chunk256": 4.65,
                            "chunk512": 4.62, "chunk768": 4.64,
                        },
                    },
                },
            }
        )
    )
    if r3_gate and r3_gate["hard"] and not r3_gate["passed"]:
        # explicit PRIMETPU_BENCH_RUNG3_FLOOR: a miss is a regression
        sys.exit(1)


if __name__ == "__main__":
    main()
