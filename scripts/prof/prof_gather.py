"""Micro-benchmark: TPU gather/scatter cost vs index count, row width,
and operand size — the data behind the engine's array-layout choices.

Hypothesis from prof_bisect deltas: cost ~= per-INDEX overhead (~80 ns),
mostly independent of row width and operand bytes; windowed (dynamic
column) forms are pathological. If true, fusing metadata columns into the
sharers rows (one gather per probe instead of three) is the right call.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    rng = np.random.default_rng(0)
    R = 524288
    for width in (8, 24, 128, 280, 384):
        A = jnp.asarray(rng.integers(0, 100, (R, width), dtype=np.int32))
        for n_idx in (1024, 4096, 9216, 18432):
            idx = jnp.asarray(rng.integers(0, R, n_idx, dtype=np.int32))
            t_row = timeit(lambda a, i: a[i], A, idx)
            col = jnp.asarray(
                rng.integers(0, width, n_idx, dtype=np.int32)
            )
            t_el = timeit(lambda a, i, c: a[i, c], A, idx, col)
            upd = jnp.zeros((n_idx, width), jnp.int32)
            t_sc = timeit(
                lambda a, i, u: a.at[i].set(u, mode="drop"), A, idx, upd
            )
            print(
                f"w={width:4d} n={n_idx:6d}  row-gather {t_row*1e3:7.3f} ms"
                f"  elem-gather {t_el*1e3:7.3f} ms"
                f"  row-scatter {t_sc*1e3:7.3f} ms",
                flush=True,
            )


if __name__ == "__main__":
    main()
