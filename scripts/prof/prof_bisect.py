"""Bisect per-step cost: stub out pieces of engine.step via source surgery.

Each variant knocks out ONE piece of the step (replacing it with a cheap
stand-in of the same shape) and times a 256-step `run_chunk` at the
flagship 1024-core config. The simulated behavior diverges under ablation
(that's fine — step cost is shape-static, not data-dependent), so this is
a TIMING tool only. Patterns are exact substrings of the current
`engine.py`; `build()` asserts they still exist so the tool rots loudly,
not silently (round-2 lesson).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import primesim_tpu.sim.engine as eng_mod
from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins

SRC = open(eng_mod.__file__).read()

VARIANTS = {
    "full": [],
    "no_dirm_scatter": [
        ('    dirm_n = st.dirm.at[upd_slot].add(delta_row, mode="drop")',
         "    dirm_n = st.dirm"),
    ],
    "no_joinrep_table": [
        ('    jtab = jnp.full(B * S2 * W2, INT32_MAX, jnp.int32).at[jsw].min(\n        key, mode="drop"\n    )',
         "    jtab = jnp.full(B * S2 * W2, INT32_MAX, jnp.int32)"),
    ],
    "no_unpack_CC": [
        ("        sh_bits = unpack_bits(shw)",
         "        sh_bits = jnp.zeros((C, C), bool)"),
        ("        vic_sh_bits = unpack_bits(vic_shw)",
         "        vic_sh_bits = jnp.zeros((C, C), bool)"),
    ],
    "no_arb_table": [
        ('    table = table.at[jnp.where(req, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
        ('    table = table.at[jnp.where(demoted, slot, B * S2)].min(key, mode="drop")',
         "    table = table"),
    ],
    "no_l1_scatter": [
        ("    l1_n = l1_c.at[", "    l1_n = l1_c; _dead = l1_c.at["),
    ],
    "no_ptr_gathers": [
        ("    vtag = dirm[pslot, 2 * pway]  # [C, W1]",
         "    vtag = tag_rows"),
        ("    vown = dirm[pslot, 2 * pway + 1]",
         "    vown = jnp.broadcast_to(arange_c[:, None], tag_rows.shape)"),
        ("    vsh = dirm[pslot, MW + pway * NW + (g_c[:, None] >> 5)]",
         "    vsh = jnp.zeros(tag_rows.shape, jnp.int32)"),
    ],
    "no_phase1_validation": [
        ("    return jnp.where(\n        (state_rows == I) | (vtag != tag_rows),\n        I,\n        jnp.where(\n            vown == arange_c[:, None],\n            state_rows,\n            jnp.where(vbit, S, I),\n        ),\n    )  # [C, W1] effective MESI per way",
         "    return state_rows"),
    ],
    "no_dirmrows_gather": [
        ("    meta_rows = st.dirm[slot]  # [C, DW]: the set\'s metadata AND sharers",
         "    meta_rows = jnp.full((C, st.dirm.shape[1]), -1, jnp.int32)"),
    ],
    "no_run_prefetch_rows": [
        ("        pmrows = st.dirm[pslot]  # [C, rl+1, DW] — metadata AND sharers",
         "        pmrows = jnp.full((C, rl + 1, st.dirm.shape[1]), -1, jnp.int32)"),
    ],
}


def build(name):
    src = SRC
    for old, new in VARIANTS[name]:
        assert old in src, f"{name}: pattern not found: {old[:60]!r}"
        src = src.replace(old, new)
    ns = {
        "__name__": f"primesim_tpu.sim.engine_{name}",
        "__package__": "primesim_tpu.sim",
        "__file__": eng_mod.__file__,
    }
    exec(compile(src, eng_mod.__file__, "exec"), ns)
    return ns["run_chunk"]


def main():
    import os

    C = 1024
    rl = int(os.environ.get("PRIMETPU_PROF_RL", "0"))
    cfg = MachineConfig(n_cores=C, n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100, quantum=1000, local_run_len=rl)
    print(f"local_run_len={rl}")
    trace = fold_ins(synth.fft_like(C, n_phases=2, points_per_core=16, ins_per_mem=8, seed=42))
    events = jnp.asarray(trace.line_events(cfg.line_bits))
    n = 256
    base = None
    for name in VARIANTS:
        rc = build(name)
        st = init_state(cfg)
        out = rc(cfg, n, events, st)
        np.asarray(out.step)  # sync after warm-up/compile
        t0 = time.perf_counter()
        for _ in range(3):
            out = rc(cfg, n, events, out)
        np.asarray(out.step)  # sync
        dt = (time.perf_counter() - t0) / 3 / n
        if name == "full":
            base = dt
        delta = "" if base is None else f"  (saves {1e3*(base-dt):+.3f})"
        print(f"[{name:22s}] {dt*1e3:.3f} ms/step{delta}", flush=True)


if __name__ == "__main__":
    main()
