"""Cumulative phase bisect: truncate engine.step at successive phase
markers and time each prefix — pinpoints which PHASE the per-step
milliseconds live in (prof_bisect ablates single ops; this localizes).

Source surgery like prof_bisect: each cut keeps everything computed so
far alive via a data-dependent guard (so DCE can't erase the phase) and
returns a well-formed MachineState. TIMING tool only — simulated
behavior diverges beyond the cut.
"""
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

import primesim_tpu.sim.engine as eng_mod
from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins

SRC = open(eng_mod.__file__).read()

# (name, marker to cut just BEFORE, keep-alive expression over live vars)
CUTS = [
    ("p0_quantum", "# ---- phase 0.5", "quantum_end"),
    ("p05_localrun", "# ---- phase 0.9", "cycles_c + ptr_c"),
    ("p09_arbevent", "# LLC lookup for the accessed line",
     "cycles_c + ptr_c + et + eaddr + weff.sum(1) + hit_way"),
        ("p1_llcrows", "# ---- phase 2:",
     "cycles_c + ptr_c + weff.sum(1) + llc_hway + owner + self_bit + et"),
    ("p2_arb", "# ---- phase 3:",
     "cycles_c + ptr_c + weff.sum(1) + owner + winner + join + retry + et"),
    ("p3_inv", "# --- latency composition",
     "cycles_c + ptr_c + weff.sum(1) + winner + inv_lat + inv_count"
     " + back_count + vic_owner + et"),
    ("p3_lat", "# --- granted L1 state",
     "cycles_c + ptr_c + weff.sum(1) + winner + lat + lat_join + et"),
    ("p4_counters", "# ---- phase 4.A",
     "cycles_c + ptr_c + weff.sum(1) + winner + lat + noc_msgs + et"),
    # keep expr must resolve under BOTH step impls: the xla branch binds
    # l1_n at this cut, the pallas branch binds commit_lanes instead
    ("p4a_l1", "# Directory update:",
     "cycles + ptr + lat + (l1_n.sum(1) if cfg.step_impl == 'xla'"
     " else commit_lanes.sum(1))"),
    ("full", None, None),
]

RET = """
    _keep = {keep}
    _g = jnp.where(jnp.sum(_keep) == jnp.int32(-123454321), 1, 0)
    return st._replace(cycles=st.cycles + _g, step=step_no + 1)
"""


def build(name, marker, keep):
    src = SRC
    if marker is not None:
        i = src.index(marker)
        # cut at the start of the marker's line
        i = src.rfind("\n", 0, i) + 1
        src = src[:i] + RET.format(keep=keep)
        # keep module-level code after step(): find next top-level def in
        # ORIGINAL source and append everything from there
        j = SRC.index("\n@functools.partial(")
        src = src + SRC[j:]
    ns = {
        "__name__": f"primesim_tpu.sim.engine_{name}",
        "__package__": "primesim_tpu.sim",
        "__file__": eng_mod.__file__,
    }
    exec(compile(src, eng_mod.__file__, "exec"), ns)
    return ns["run_chunk"]


def phase_cuts(cfg, trace, n_steps: int = 256, repeats: int = 3):
    """Measure every cumulative phase cut on (cfg, trace): returns an
    ordered {cut_name: ms_per_step} dict (each entry includes everything
    before it; successive deltas localize a phase's cost). This is the
    callable form bench.py folds into its BENCH detail — same source
    surgery, caller's config (works under either step_impl)."""
    events = jnp.asarray(trace.line_events(cfg.line_bits))
    out_ms = {}
    for name, marker, keep in CUTS:
        rc = build(name, marker, keep)
        st = init_state(cfg)
        out = rc(cfg, n_steps, events, st)
        np.asarray(out.step)  # compile + first run outside the clock
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = rc(cfg, n_steps, events, out)
        np.asarray(out.step)
        dt = (time.perf_counter() - t0) / repeats / n_steps
        out_ms[name] = dt * 1e3
    return out_ms


def main():
    C = 1024
    rl = int(os.environ.get("PRIMETPU_PROF_RL", "8"))
    impl = os.environ.get("PRIMETPU_PROF_STEP_IMPL", "xla")
    cfg = MachineConfig(n_cores=C, n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=256 * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100, quantum=1000, local_run_len=rl, step_impl=impl)
    print(f"local_run_len={rl} step_impl={impl}")
    trace = fold_ins(synth.fft_like(C, n_phases=2, points_per_core=16,
                                    ins_per_mem=8, seed=42))
    prev = 0.0
    for name, ms in phase_cuts(cfg, trace).items():
        print(f"[{name:14s}] {ms:7.3f} ms/step  (+{ms - prev:6.3f})",
              flush=True)
        prev = ms


if __name__ == "__main__":
    main()
