"""Where do the rung-3 ms go? Phase cuts for the FIFO-contention path.

Self-contained component timings at the SHIPPED rung-3 shapes
(`configs/rung3_1024core_o3.json`: 1024 cores, 32x32 mesh -> H=62 hop
columns, 4096 directed links, 1024 DRAM banks) isolating the three
costs of the router + DRAM-queue step tail (DESIGN.md §13):

- `rank`: the same-step FIFO rank primitive — the shipped sort-based
  `ops.ranking.segmented_rank` (O(E log E)) vs the retired one-hot
  matmul formulation ([C,C] int8 kless x [C,NL] one-hot, O(C^2 * NL)
  MACs) it replaced, at identical shapes. This is the cut that moved
  rung 3 from ~1296 to ~67 ms/step on a 1-core CPU container.
- `cascade`: the wait-floor + per-leg cummax cascade + departures, XLA
  closed form vs the fused Pallas kernel (`kernels.router_kernels`,
  interpreter mode off-TPU — so on CPU this row measures the interpreter,
  not Mosaic; compare on TPU for the real kernel number).
- `scatter`: the data-dependent edges that stay XLA on purpose — the
  base scatter-min, the per-hop link_free/base gather pair, and the
  departure scatter-max back into link_free.

Plus whole-step ms/step on the full rung-3 machine for both
`step_impl=xla` and `=pallas` (the end-to-end number the components
should sum toward). No source surgery — everything here calls shipped
entry points, so this tool cannot rot silently.

Usage: `python scripts/prof/prof_router.py` · env:
`PRIMETPU_PROF_MATMUL=0` skips the retired-matmul reference row (it is
deliberately the slow one), `PRIMETPU_PROF_STEPS` (default 16) sizes
the whole-step chunks.
"""
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from primesim_tpu.config.machine import MachineConfig
from primesim_tpu.kernels.router_kernels import SENT, router_cascade
from primesim_tpu.ops.ranking import lane_order, segmented_rank
from primesim_tpu.sim.engine import run_chunk
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins

R3 = os.path.join(os.path.dirname(__file__), "..", "..", "configs",
                  "rung3_1024core_o3.json")


def timed(fn, *args, runs=3, tag=""):
    """jit + compile warm-up + best-of-N; host-transfer sync (np.asarray
    of a leaf — the round-3 under-sync lesson, see prof_step.py)."""
    f = jax.jit(fn)
    out = f(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    walls = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = f(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        walls.append(time.perf_counter() - t0)
    ms = min(walls) * 1e3
    print(f"[{tag}] {ms:.3f} ms", flush=True)
    return ms


def router_shapes(cfg, seed=0):
    """Random operands at the engine's router-block shapes: per-lane
    FIFO keys, per-(lane,slot) link targets (within-lane distinct, the
    contract segmented_rank assumes), wait floors, masks."""
    rng = np.random.default_rng(seed)
    C = cfg.n_cores
    NL = cfg.n_tiles * 4
    H = max(1, (cfg.noc.mesh_x - 1) + (cfg.noc.mesh_y - 1))
    LT = 3 * H  # req + rep + barrier-arrival legs
    key = jnp.asarray(
        rng.integers(0, 1 << 20, C).astype(np.int32) * C
        + np.arange(C, dtype=np.int32)
    )
    base_l = rng.integers(0, NL - LT, (C, 1)).astype(np.int32)
    tgt = jnp.asarray(base_l + np.arange(LT, dtype=np.int32)[None, :])
    ok = jnp.asarray(rng.random((C, LT)) < 0.7)
    tgt = jnp.where(ok, tgt, NL)
    lf = jnp.asarray(rng.integers(0, 1000, (C, LT)).astype(np.int32))
    bs = jnp.asarray(rng.integers(0, 1000, (C, LT)).astype(np.int32))
    t0 = jnp.asarray(rng.integers(0, 500, C).astype(np.int32))
    sv = jnp.asarray(rng.integers(1, 80, C).astype(np.int32))
    nh = jnp.asarray(rng.integers(0, H + 1, (3, C)).astype(np.int32))
    return dict(C=C, NL=NL, H=H, LT=LT, key=key, tgt=tgt, ok=ok,
                lf=lf, bs=bs, t0=t0, sv=sv, nh=nh)


def rank_cuts(s):
    def sort_rank(key, tgt):
        return segmented_rank(tgt, n_seg=s["NL"], order=lane_order(key))

    def matmul_rank(key, tgt):
        # the retired formulation: strict-less MXU product against the
        # per-slot one-hot competitor matrix, then per-slot gather
        kless = (key[None, :] < key[:, None]).astype(jnp.int8)
        seg = jnp.clip(tgt, 0, s["NL"] - 1)
        U = jnp.zeros((s["C"], s["NL"]), jnp.int8)
        U = U.at[jnp.arange(s["C"])[:, None], seg].set(1)
        full = jax.lax.dot_general(
            kless, U, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return jnp.take_along_axis(full, seg, axis=1)

    timed(sort_rank, s["key"], s["tgt"], tag="rank: sort segmented_rank")
    if os.environ.get("PRIMETPU_PROF_MATMUL", "1") != "0":
        timed(matmul_rank, s["key"], s["tgt"],
              tag="rank: retired one-hot matmul")


def cascade_cuts(s, cfg):
    H, LT = s["H"], s["LT"]
    L_lat = jnp.int32(cfg.noc.link_lat)
    R_lat = jnp.int32(cfg.noc.router_lat)
    r = segmented_rank(s["tgt"], n_seg=s["NL"], order=lane_order(s["key"]))

    def xla_cascade(lf, bs, r, ok, t0, sv, nh):
        c_hop = L_lat + R_lat
        hidx = jnp.arange(H, dtype=jnp.int32)[None, :]
        F = jnp.where(ok, jnp.maximum(lf, bs) + r * L_lat, SENT)

        def leg(t_start, Fl, n):
            G = Fl - hidx * c_hop
            cum = jax.lax.cummax(G, axis=1)
            t1 = t_start + R_lat
            t_end = jnp.maximum(t1, cum[:, -1]) + n * c_hop
            return t_end, jnp.maximum(t1[:, None], cum) + hidx * c_hop + L_lat

        te_req, d_req = leg(t0, F[:, :H], nh[0])
        te_rep, d_rep = leg(te_req + sv, F[:, H:2 * H], nh[1])
        te_arr, d_arr = leg(t0, F[:, 2 * H:], nh[2])
        return te_rep, te_arr, jnp.concatenate([d_req, d_rep, d_arr], axis=1)

    def pallas_cascade(lf, bs, r, ok, t0, sv, nh):
        return router_cascade(lf, bs, r, ok, t0, sv, nh[0], nh[1], nh[2],
                              L_lat, R_lat, has_sync=True)

    a = (s["lf"], s["bs"], r, s["ok"], s["t0"], s["sv"], s["nh"])
    timed(xla_cascade, *a, tag="cascade: xla closed form")
    kind = "mosaic" if jax.default_backend() == "tpu" else "interpreter"
    timed(pallas_cascade, *a, tag=f"cascade: pallas kernel ({kind})")


def scatter_cuts(s):
    NL, LT = s["NL"], s["LT"]
    link_free = jnp.zeros(NL, jnp.int32)
    d_all = s["lf"] + 7

    def base_min_gather(key, tgt, ok):
        key_s = jnp.where(ok, key[:, None], jnp.int32((1 << 31) - 1))
        base = jnp.full(NL + 1, (1 << 31) - 1, jnp.int32)
        base = base.at[tgt].min(key_s, mode="drop")[:NL]
        pc = jnp.clip(tgt, 0, NL - 1)
        return link_free[pc], base[pc]

    def depart_max(tgt, d):
        return link_free.at[tgt].max(d, mode="drop")

    timed(base_min_gather, s["key"], s["tgt"], s["ok"],
          tag="scatter: base min + per-hop gather pair")
    timed(depart_max, s["tgt"], d_all, tag="scatter: departure max")


def whole_step(cfg, step_impl, n_steps):
    cfg = (cfg if cfg.step_impl == step_impl
           else __import__("dataclasses").replace(cfg, step_impl=step_impl))
    trace = fold_ins(synth.fft_like(
        cfg.n_cores, n_phases=2, points_per_core=16, ins_per_mem=8, seed=42))
    events = jnp.asarray(trace.line_events(cfg.line_bits))
    st = init_state(cfg)
    st = run_chunk(cfg, n_steps, events, st, has_sync=True)
    np.asarray(st.step)
    t0 = time.perf_counter()
    for _ in range(2):
        st = run_chunk(cfg, n_steps, events, st, has_sync=True)
    np.asarray(st.step)
    ms = (time.perf_counter() - t0) / 2 / n_steps * 1e3
    print(f"[whole rung-3 step: {step_impl}] {ms:.3f} ms/step", flush=True)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    with open(R3) as f:
        cfg = MachineConfig.from_json(f.read())
    s = router_shapes(cfg)
    print(f"shapes: C={s['C']} NL={s['NL']} H={s['H']} legs*H={s['LT']}")
    rank_cuts(s)
    cascade_cuts(s, cfg)
    scatter_cuts(s)
    n = int(os.environ.get("PRIMETPU_PROF_STEPS", "16"))
    whole_step(cfg, "xla", n)
    whole_step(cfg, "pallas", n)
