"""Ad-hoc step profiler: where do the 2.7 ms go at 1024 cores?"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.sim.engine import run_chunk
from primesim_tpu.sim.state import init_state
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import fold_ins


def bench_cfg(C=1024, llc_kb=256, **kw):
    return MachineConfig(
        n_cores=C,
        n_banks=C,
        l1=CacheConfig(size=32 * 1024, ways=4, line=64, latency=2),
        llc=CacheConfig(size=llc_kb * 1024, ways=8, line=64, latency=10),
        noc=NocConfig(mesh_x=32, mesh_y=32, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=1000,
        **kw,
    )


def time_chunk(cfg, n_steps=256, tag="", has_sync=False):
    trace = fold_ins(synth.fft_like(cfg.n_cores, n_phases=4, points_per_core=256,
                                    ins_per_mem=8, seed=42))
    events = jnp.asarray(trace.line_events(cfg.line_bits))
    st = init_state(cfg)
    # NOTE: sync via an explicit host transfer (np.asarray of a leaf).
    # jax.block_until_ready on AOT-compiled outputs under-synced through
    # the remote-TPU tunnel and reported ~1000x-too-fast times (round 3).
    st2 = run_chunk(cfg, n_steps, events, st, has_sync=has_sync)
    np.asarray(st2.step)
    t0 = time.perf_counter()
    for _ in range(3):
        st2 = run_chunk(cfg, n_steps, events, st2, has_sync=has_sync)
    np.asarray(st2.step)
    dt = (time.perf_counter() - t0) / 3 / n_steps
    print(f"[{tag}] {dt*1e3:.3f} ms/step", flush=True)
    return dt


if __name__ == "__main__":
    print("devices:", jax.devices())
    time_chunk(bench_cfg(1024), tag="1024c full")
    time_chunk(bench_cfg(1024, llc_kb=64), tag="1024c llc64KB (1/4 sets)")
    time_chunk(bench_cfg(256), tag="256c full")
