"""Time run_chunk at the BENCH machine across local_run_len values — the
number the perf work must move. Reuses prof_step's harness (one config
builder + timing protocol; see the sync NOTE there).

Usage: python prof_rl.py [rl ...]       (default: 0 8)
"""
import sys

from prof_step import bench_cfg, time_chunk


def main():
    rls = [int(a) for a in sys.argv[1:]] or [0, 8]
    for rl in rls:
        time_chunk(bench_cfg(1024, local_run_len=rl), tag=f"rl={rl}")


if __name__ == "__main__":
    main()
