#!/usr/bin/env bash
# Tier-1 verify gate — the EXACT command from ROADMAP.md (keep in sync).
# Runs the fast test suite on CPU, prints DOTS_PASSED (count of passing
# dots parsed from pytest's progress lines), exits with pytest's status.
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# invariant lint gate (DESIGN.md §19): the tree + committed baseline must
# have zero findings. Pytest's status stays authoritative — the lint
# result is only surfaced when the suite itself passed.
if [ "$rc" -eq 0 ]; then
  env JAX_PLATFORMS=cpu python -m primesim_tpu lint || rc=$?
fi
exit $rc
