"""Golden-vs-JAX bit-exact parity (SURVEY.md §4a — the core fidelity test).

Every workload generator, several machine shapes: per-core cycles, trace
pointers, all cache/directory state, and every stat counter must match the
golden model EXACTLY.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.sim.validate import (
    I,
    effective_l1_state,
    engine_l1_to_golden,
    epoch_views,
    l1_views,
    llc_views,
    sharers_view,
)
from primesim_tpu.trace import synth


def machine(n_cores=8, **kw):
    d = dict(
        n_cores=n_cores,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=64, latency=10),
        n_banks=max(2, n_cores // 2),
        noc=NocConfig(mesh_x=2, mesh_y=2, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=500,
    )
    d.update(kw)
    return MachineConfig(**d)


def assert_parity(cfg, trace, chunk_steps=64):
    from primesim_tpu.sim.engine import Engine

    g = GoldenSim(cfg, trace)
    g.run()
    e = Engine(cfg, trace, chunk_steps=chunk_steps)
    e.run()

    np.testing.assert_array_equal(e.cycles, g.cycles, err_msg="cycles")
    np.testing.assert_array_equal(np.asarray(e.state.ptr), g.ptr, err_msg="ptr")
    # The engine's L1 arrays hold only locally-written state (pull-based
    # coherence); the golden's eager MESI state must equal the engine's
    # directory-VALIDATED state at every way, with matching tags wherever
    # the golden holds a valid line. This is the empirical proof of the
    # eager/pull equivalence (DESIGN.md §7).
    e_llc_tag, e_llc_owner, e_llc_lru = llc_views(cfg, e.state)
    e_l1_tag2, e_l1_state2, e_l1_lru2, _ = l1_views(cfg, e.state)
    e_l1_eph, e_llc_eph = (
        epoch_views(cfg, e.state) if cfg.sharer_group > 1 else (None, None)
    )
    eff = effective_l1_state(
        cfg,
        e_l1_tag2,
        e_l1_state2,
        e_llc_tag,
        e_llc_owner,
        sharers_view(cfg, e.state),
        l1_eph=e_l1_eph,
        llc_eph=e_llc_eph,
    )
    np.testing.assert_array_equal(eff, g.l1_state, err_msg="effective l1_state")
    valid = g.l1_state != I
    e_l1_tag = engine_l1_to_golden(cfg, e_l1_tag2)
    np.testing.assert_array_equal(
        np.where(valid, e_l1_tag, -1),
        np.where(valid, g.l1_tag, -1),
        err_msg="l1_tag (valid ways)",
    )
    np.testing.assert_array_equal(e_llc_tag, g.llc_tag, err_msg="llc_tag")
    np.testing.assert_array_equal(e_llc_owner, g.llc_owner, err_msg="llc_owner")
    # engine stores sharers row-per-(bank,set) with ways folded into the
    # fused dirm rows' tail columns
    np.testing.assert_array_equal(
        sharers_view(cfg, e.state).reshape(g.sharers.shape),
        g.sharers,
        err_msg="sharers",
    )
    # synchronization state (phase 2.7): lock table, barrier tables, flags
    np.testing.assert_array_equal(
        np.asarray(e.state.lock_holder), g.lock_holder, err_msg="lock_holder"
    )
    np.testing.assert_array_equal(
        np.asarray(e.state.barrier_count), g.barrier_count, err_msg="barrier_count"
    )
    np.testing.assert_array_equal(
        np.asarray(e.state.sync_flag), g.sync_flag, err_msg="sync_flag"
    )
    ec = e.counters
    for k, v in g.counters.items():
        np.testing.assert_array_equal(ec[k], v, err_msg=f"counter {k}")
    # LRU parity (modulo int width): compare where entries are valid
    np.testing.assert_array_equal(
        engine_l1_to_golden(cfg, e_l1_lru2),
        g.l1_lru,
        err_msg="l1_lru",
    )
    np.testing.assert_array_equal(e_llc_lru, g.llc_lru, err_msg="llc_lru")


GENS = {
    "uniform_random": lambda n: synth.uniform_random(n, n_mem_ops=80, seed=11),
    "stream": lambda n: synth.stream(n, n_mem_ops=60, seed=12),
    "pointer_chase": lambda n: synth.pointer_chase(n, n_mem_ops=60, seed=13),
    "false_sharing": lambda n: synth.false_sharing(n, n_mem_ops=60, seed=14),
    "fft_like": lambda n: synth.fft_like(n, n_phases=2, points_per_core=12, seed=15),
    "readers_writer": lambda n: synth.readers_writer(n, n_rounds=3, seed=16),
    "lock_contention": lambda n: synth.lock_contention(n, n_critical=8, seed=17),
    "barrier_phases": lambda n: synth.barrier_phases(n, n_phases=2, seed=18),
}


@pytest.mark.parametrize("gen", sorted(GENS))
def test_parity_8core(gen):
    cfg = machine(8)
    assert_parity(cfg, GENS[gen](8))


@pytest.mark.parametrize("gen", ["uniform_random", "false_sharing", "fft_like"])
def test_parity_16core_small_quantum(gen):
    # tiny quantum stresses the barrier; small LLC stresses back-invalidation
    cfg = machine(
        16,
        n_banks=4,
        llc=CacheConfig(size=2048, ways=2, line=64, latency=7),
        noc=NocConfig(mesh_x=4, mesh_y=2, link_lat=2, router_lat=1),
        quantum=64,
    )
    assert_parity(cfg, GENS[gen](16), chunk_steps=50)


def test_parity_heterogeneous_cpi():
    from primesim_tpu.config.machine import CoreConfig

    cfg = machine(8)
    import dataclasses

    cfg = dataclasses.replace(
        cfg, core=CoreConfig(cpi_per_core=tuple([1, 2] * 4))
    )
    assert_parity(cfg, GENS["uniform_random"](8))


def test_parity_o3_overlap():
    from primesim_tpu.config.machine import CoreConfig
    import dataclasses

    cfg = dataclasses.replace(machine(8), core=CoreConfig(cpi=1, o3_overlap_256=128))
    assert_parity(cfg, GENS["fft_like"](8))


@pytest.mark.parametrize("gen", sorted(GENS))
def test_parity_local_runs(gen):
    # local_run_len > 0: cores retire runs of INS/L1-hit events before the
    # arbitrated event each step (DESIGN.md §3 "local runs"); must stay
    # bit-exact vs golden on every generator
    cfg = machine(8, local_run_len=4)
    assert_parity(cfg, GENS[gen](8))


def test_parity_local_runs_folded_small_quantum():
    from primesim_tpu.trace.format import fold_ins

    cfg = machine(16, n_banks=4, quantum=64, local_run_len=8)
    assert_parity(cfg, fold_ins(GENS["fft_like"](16)), chunk_steps=50)


def test_parity_single_core():
    cfg = machine(1, n_banks=1, noc=NocConfig(mesh_x=1, mesh_y=1))
    assert_parity(cfg, GENS["pointer_chase"](1))


def test_parity_non_pow2_cores():
    # non-pow2 core counts are legal (big.LITTLE mixes, odd device meshes);
    # only banks/sets/line need pow2 mask arithmetic
    cfg = machine(12, n_banks=4)
    assert_parity(cfg, GENS["false_sharing"](12))


@pytest.mark.slow
def test_parity_folded_traces():
    # fold_ins moves INS batches into mem events' pre field (pre > 0 paths);
    # golden and engine must stay bit-exact on the folded representation
    from primesim_tpu.trace.format import fold_ins

    for name in ("uniform_random", "false_sharing", "fft_like"):
        cfg = machine(8)
        assert_parity(cfg, fold_ins(GENS[name](8)))


def test_parity_rejoin_after_silent_eviction():
    # Regression (ADVICE r1, high): a sharer whose L1 copy was silently
    # evicted still has its directory bit set; when it re-reads the line as
    # a coalesced join, the engine's sharer scatter-ADD must not carry into
    # the adjacent bit (golden's _set_sharer is idempotent).
    from primesim_tpu.trace.format import EV_INS, EV_LD, from_event_lists

    cfg = machine(4)  # l1: 8 sets x 2 ways; lines 0, 8, 16 share L1 set 0
    trace = from_event_lists(
        [
            [
                (EV_INS, 100, 0),  # let core 1 take ownership first
                (EV_LD, 4, 0),     # probe owner -> sharers {0,1}, owner -1
                (EV_LD, 4, 8 * 64),   # conflicting fill (L1 set 0)
                (EV_LD, 4, 16 * 64),  # second fill silently evicts line 0
                (EV_LD, 4, 0),     # re-read: join with stale self-bit set
            ],
            [(EV_LD, 4, 0)],  # first reader, then idle
            [],
            [],
        ]
    )
    assert_parity(cfg, trace)
    # and the sharer set for line 0 must be exactly {0, 1}
    g = GoldenSim(cfg, trace)
    g.run()
    assert g._sharers_from(g.sharers, 0, 0, 0) == [0, 1]


def test_fold_ins_preserves_instructions():
    from primesim_tpu.trace.format import EV_INS, fold_ins

    tr = GENS["fft_like"](8)
    folded = fold_ins(tr)
    assert folded.total_instructions() == tr.total_instructions()
    # folded traces should have (almost) no standalone INS events left
    t = folded.events[:, :, 0]
    assert (t == EV_INS).sum() <= folded.n_cores  # at most one trailing per core
    assert folded.max_len < tr.max_len
