"""Tests for unified elastic serving (DESIGN.md §18): the TCP front
door, per-tenant quotas, segmented-journal durability (roll / chain
verification / compaction), the v2 paged allocator's bucket migration
(promotion + demotion) bit-exactness, the dispatch scheduler's admission
surface, and the real-process kill matrix.

Determinism discipline matches test_serve.py / test_pool.py: fast tests
pin semantics in-process (fake clocks, no subprocesses); the kill-matrix
acceptance tests (real SIGKILL of the front-end, the coordinator, a
worker, and front-end+worker together, with two concurrent TCP clients)
are @slow — tier-1 excludes them, the CI unified-chaos job runs them.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.serve import (
    Job,
    JobJournal,
    JournalCorrupt,
    Scheduler,
    fold_records,
)
from primesim_tpu.serve.client import ServeClient, ServeError
from primesim_tpu.serve.journal import serve_compactor
from primesim_tpu.serve.protocol import ServeUnavailable, parse_target
from primesim_tpu.serve.quota import QuotaExceeded, TenantQuota
from primesim_tpu.serve.scheduler import QueueFull

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: 81 events/core: does NOT fit a 1-page (64-event) slot, fits 8 pages —
#: the window-admission shape (sync-free, so windowing is legal)
WINDOW_SYNTH = "stream:n_mem_ops=80,seed={}"
#: 201 events/core: several pages, ~13 chunks at chunk_steps=16 — long
#: enough that a kill lands mid-flight
KILL_SYNTH = "stream:n_mem_ops=200,seed={}"


def _cfg():
    return small_test_config(4)


def _job(i, synth, **kw):
    return Job(job_id=f"j{i:06d}", synth=synth, **kw)


def _run_all(sched, jobs, limit=5000):
    n = 0
    while not all(j.terminal for j in jobs):
        sched.tick()
        n += 1
        assert n < limit, [j.state for j in jobs]


def _solo_result(cfg, synth_spec, chunk_steps=16):
    from primesim_tpu.serve.scheduler import parse_synth_spec
    from primesim_tpu.sim.engine import Engine

    eng = Engine(cfg, parse_synth_spec(synth_spec, cfg.n_cores, True),
                 chunk_steps=chunk_steps)
    eng.run()
    return (
        [int(c) for c in eng.cycles],
        {k: [int(x) for x in v] for k, v in eng.counters.items()},
    )


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---- target parsing ------------------------------------------------------


def test_parse_target_forms():
    assert parse_target("/tmp/x/serve.sock") == ("unix", "/tmp/x/serve.sock")
    assert parse_target("state/serve.sock") == ("unix", "state/serve.sock")
    assert parse_target("127.0.0.1:9000") == ("tcp", ("127.0.0.1", 9000))
    assert parse_target("host.example:80") == ("tcp", ("host.example", 80))
    assert parse_target("[::1]:9000") == ("tcp", ("::1", 9000))
    # a colon does not make a TCP target unless the port parses and the
    # string cannot be a path
    assert parse_target("dir/with:colon")[0] == "unix"
    assert parse_target("host:notaport")[0] == "unix"
    assert parse_target(":9000")[0] == "unix"  # empty host


# ---- per-tenant quotas ---------------------------------------------------


def test_quota_token_bucket_admit_reject_refill():
    clk = FakeClock()
    q = TenantQuota(rate=1.0, burst=2.0, clock=clk)
    q.admit("a")
    q.admit("a")  # burst exhausted
    with pytest.raises(QuotaExceeded) as ei:
        q.admit("a")
    assert ei.value.client == "a"
    assert ei.value.retry_after_s == pytest.approx(1.0)
    assert q.rejections == 1
    # tenants are isolated: b's bucket is untouched by a's rejection
    q.admit("b")
    # refill is exact: after retry_after_s one token exists again
    clk.advance(1.0)
    q.admit("a")
    with pytest.raises(QuotaExceeded):
        q.admit("a")
    assert q.rejections == 2


def test_quota_parse_forms():
    q = TenantQuota.parse("2")
    assert q.rate == 2.0 and q.burst == 2.0
    q = TenantQuota.parse("0.5:10")
    assert q.rate == 0.5 and q.burst == 10.0
    # rate below one token/s still gets a usable burst of one
    assert TenantQuota.parse("0.25").burst == 1.0
    with pytest.raises(ValueError):
        TenantQuota.parse("0")
    with pytest.raises(ValueError):
        TenantQuota(rate=2.0, burst=0.5)


def test_quota_rejection_on_the_wire(tmp_path):
    """A drained tenant bucket surfaces as the same structured
    retry_after_s backpressure shape QueueFull uses — over a real TCP
    listener (the unified front door)."""
    from primesim_tpu.serve.server import PrimeServer

    server = PrimeServer(
        _cfg(), state_dir=str(tmp_path / "srv"),
        socket_path="127.0.0.1:0", buckets=((2, 1),), chunk_steps=16,
        quota=TenantQuota(rate=0.001, burst=1.0),
    )
    # listener + inbox pump only — no tick loop, jobs just queue
    listener = server._make_listener()
    t = threading.Thread(target=listener.serve_forever, daemon=True)
    t.start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            server._drain_inbox()
            time.sleep(0.005)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    try:
        assert parse_target(server.socket_path)[0] == "tcp"
        cli = ServeClient(server.socket_path, timeout_s=30.0)
        cli.submit(synth=WINDOW_SYNTH.format(1), client="tenant-a")
        with pytest.raises(ServeError) as ei:
            cli.submit(synth=WINDOW_SYNTH.format(2), client="tenant-a")
        assert ei.value.error["type"] == "QuotaExceeded"
        assert ei.value.retry_after_s is not None
        health = cli._call({"verb": "health"})
        assert health["quota"]["rejections"] == 1
        metrics = cli.metrics()
        assert "primetpu_quota_rejections_total 1" in metrics
    finally:
        stop.set()
        listener.shutdown()
        listener.server_close()


# ---- client failover window ----------------------------------------------


def test_client_retries_connect_failure_once(monkeypatch):
    calls = {"n": 0}

    def flaky(target, req, timeout_s=30.0, connect_timeout_s=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ServeUnavailable("front-end restarting")
        return {"ok": True, "queue_depth": 0}

    monkeypatch.setattr("primesim_tpu.serve.client.request", flaky)
    cli = ServeClient("127.0.0.1:9999", timeout_s=1.0)
    assert cli._call({"verb": "health"})["queue_depth"] == 0
    assert calls["n"] == 2  # exactly one retry

    def down(target, req, timeout_s=30.0, connect_timeout_s=None):
        calls["n"] += 1
        raise ServeUnavailable("nothing listening")

    calls["n"] = 0
    monkeypatch.setattr("primesim_tpu.serve.client.request", down)
    with pytest.raises(ServeUnavailable):
        cli._call({"verb": "health"})
    assert calls["n"] == 2  # one retry, then reported down


# ---- segmented journal ---------------------------------------------------


def _seg_files(d):
    return sorted(f for f in os.listdir(d)
                  if re.match(r"journal-\d{6}\.jsonl$", f))


def test_journal_rolls_segments_and_replays_across_chain(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d, segment_records=4)
    for i in range(11):
        j.note(f"rec{i}")
    assert j.segments_rolled >= 2
    assert len(_seg_files(d)) >= 2
    recs, dropped = JobJournal(d, segment_records=4).replay()
    assert dropped == 0
    assert [r["msg"] for r in recs] == [f"rec{i}" for i in range(11)]
    j.close()


def test_journal_torn_tail_tolerated_only_in_newest_segment(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d, segment_records=4)
    for i in range(10):
        j.note(f"rec{i}")
    j.close()
    # torn tail on the ACTIVE segment: dropped, not fatal
    with open(os.path.join(d, "journal.jsonl"), "a") as f:
        f.write('{"c": 7, "r": {"t": "no')
    recs, dropped = JobJournal(d, segment_records=4).replay()
    assert len(recs) == 10 and dropped == 1
    # the SAME damage in a rolled (closed) segment is media rot
    rolled = os.path.join(d, _seg_files(d)[0])
    with open(rolled, "a") as f:
        f.write('{"c": 7, "r": {"t": "no')
    with pytest.raises(JournalCorrupt, match="closed segment"):
        JobJournal(d, segment_records=4).replay()


def test_journal_missing_middle_segment_raises(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d, segment_records=3)
    for i in range(12):
        j.note(f"rec{i}")
    j.close()
    segs = _seg_files(d)
    assert len(segs) >= 3
    os.unlink(os.path.join(d, segs[1]))
    with pytest.raises(JournalCorrupt, match="is missing"):
        JobJournal(d, segment_records=3).replay()


def test_journal_tampered_chain_crc_raises(tmp_path):
    """Swapping a rolled segment for a DIFFERENT valid segment of the
    same seq breaks the prev-CRC chain even though every line checks."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    for d, tag in ((d1, "x"), (d2, "y")):
        j = JobJournal(d, segment_records=3)
        for i in range(7):
            j.note(f"{tag}{i}")
        j.close()
    seg = _seg_files(d1)[0]
    os.replace(os.path.join(d2, seg), os.path.join(d1, seg))
    with pytest.raises(JournalCorrupt, match="chain CRC"):
        JobJournal(d1, segment_records=3).replay()


def test_serve_compaction_preserves_fold(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d, compactor=serve_compactor, segment_records=4)
    for i in range(1, 7):
        j.accept(_job(i, WINDOW_SYNTH.format(i)))
        j.state(f"j{i:06d}", "RUNNING", detail={"attempt": 1})
    j.state("j000001", "DONE", result={"cycles": 42})
    j.state("j000002", "QUARANTINED", detail={"type": "CapacityError"})
    before, _ = j.replay()
    jobs_before, clean_before = fold_records(before)

    kept = j.compact()
    assert kept < len(before)
    assert j.compactions >= 1
    # replay through a FRESH journal: the compacted base is what a
    # restarted front-end actually sees
    after, dropped = JobJournal(d).replay()
    assert dropped == 0
    jobs_after, clean_after = fold_records(
        [r for r in after if r.get("t") != "note"])
    assert clean_after == clean_before
    assert set(jobs_after) == set(jobs_before)
    for jid, jb in jobs_before.items():
        ja = jobs_after[jid]
        assert (ja.state, ja.result, ja.detail) == \
            (jb.state, jb.result, jb.detail), jid
    j.close()


def test_pool_compaction_preserves_fold(tmp_path):
    from primesim_tpu.pool.units import fold_unit_records, pool_compactor

    d = str(tmp_path / "ledger")
    j = JobJournal(d, compactor=pool_compactor)
    j.append({"t": "lease", "unit_id": "u0", "worker": "w0", "epoch": 1,
              "key": "k0", "hedge": False})
    j.append({"t": "expire", "unit_id": "u0", "worker": "w0", "epoch": 1})
    j.append({"t": "lease", "unit_id": "u0", "worker": "w1", "epoch": 2,
              "key": "k0", "hedge": False})
    j.append({"t": "ack", "unit_id": "u0", "worker": "w1", "epoch": 2,
              "key": "k0", "result": {"v": 1}, "resumed_steps": 5})
    j.append({"t": "lease", "unit_id": "u1", "worker": "w0", "epoch": 1,
              "key": "k1", "hedge": False})
    j.append({"t": "poison", "unit_id": "u2", "key": "k2",
              "kills": ["w0", "w1"]})
    before, _ = j.replay()
    units_before, clean_before = fold_unit_records(before)

    j.compact()
    after, dropped = JobJournal(d).replay()
    assert dropped == 0
    units_after, clean_after = fold_unit_records(
        [r for r in after if r.get("t") != "note"])
    assert clean_after == clean_before
    assert units_after == units_before
    j.close()


# ---- v2 paged allocator: window admission + bucket migration -------------


def _sched(tmp_path, name, buckets, chunk_steps=16):
    d = str(tmp_path / name)
    return Scheduler(_cfg(), JobJournal(d), d, buckets=buckets,
                     chunk_steps=chunk_steps, max_queue=16,
                     checkpoint_every_s=0.0)


def test_window_promotion_bit_exact(tmp_path):
    """A job too long for the only free slot is window-admitted there,
    then PROMOTED to a full-size slot (element-checkpoint migration)
    before its pointer can reach the truncated window edge — no
    quarantine, no re-simulated chunks, results bit-exact."""
    sched = _sched(tmp_path, "promo", buckets=((1, 1), (1, 8)))
    blocker = _job(1, WINDOW_SYNTH.format(1))
    windowed = _job(2, WINDOW_SYNTH.format(2))
    sched.submit(blocker)
    sched.submit(windowed)
    sched.tick()
    # blocker owns the only full-fit slot; the second job is windowed
    # into the 1-page bucket instead of waiting
    assert sched.buckets[1].slots[0] is blocker
    assert sched.buckets[0].slots[0] is windowed
    assert windowed._window is not None
    _run_all(sched, [blocker, windowed])
    assert sched.promotions >= 1
    assert sched.stats()["migrations"]["promotions"] == sched.promotions
    for j in (blocker, windowed):
        assert j.state == "DONE", (j.job_id, j.state, j.detail)
        cyc, ctr = _solo_result(sched.cfg, j.synth)
        assert j.result["core_cycles"] == cyc
        assert j.result["counters"] == ctr


@pytest.mark.slow
def test_demotion_unblocks_queued_job_bit_exact(tmp_path):
    """A small job squatting in the big bucket is DEMOTED into a free
    small slot when a queued job fits nowhere else — both finish
    bit-exact (the demoted one resumes from its migration checkpoint)."""
    sched = _sched(tmp_path, "demo", buckets=((1, 1), (1, 8)),
                   chunk_steps=8)
    tiny = _job(1, "stream:n_mem_ops=10,seed=1")   # 11 events, 2 chunks
    small = _job(2, "stream:n_mem_ops=60,seed=2")  # 61 events, 1 page
    sched.submit(tiny)
    sched.submit(small)
    sched.tick()
    assert sched.buckets[0].slots[0] is tiny
    assert sched.buckets[1].slots[0] is small  # full-fit beats waiting
    n = 0
    while not tiny.terminal:
        sched.tick()
        n += 1
        assert n < 100
    assert not small.terminal  # 8x the work: still mid-flight

    large = _job(3, KILL_SYNTH.format(3))  # only fits the 8-page bucket
    sched.submit(large)
    _run_all(sched, [tiny, small, large])
    assert sched.demotions >= 1
    assert sched.stats()["migrations"]["demotions"] == sched.demotions
    for j in (tiny, small, large):
        assert j.state == "DONE", (j.job_id, j.state, j.detail)
        cyc, ctr = _solo_result(sched.cfg, j.synth, chunk_steps=8)
        assert j.result["core_cycles"] == cyc
        assert j.result["counters"] == ctr


# ---- dispatch scheduler admission (no processes) -------------------------


def test_dispatch_scheduler_admission_and_stats(tmp_path):
    from primesim_tpu.serve.dispatch import DispatchScheduler

    d = str(tmp_path / "fe")
    sched = DispatchScheduler(
        _cfg(), JobJournal(d, compactor=serve_compactor), d,
        str(tmp_path / "pool"), buckets=((6, 1), (2, 8)), chunk_steps=16,
        max_queue=2, max_workers=3, lease_ttl_s=5.0, spawn=False,
    )
    ok = _job(1, WINDOW_SYNTH.format(1))
    sched.submit(ok)
    assert ok.state == "PENDING" and list(sched.queue) == ["j000001"]
    # the unit spec is self-contained: a worker needs nothing else
    spec = sched._unit_spec(ok)
    assert spec["serve_job"] and spec["unit_id"] == "j000001"
    assert spec["capacity_pages"] == 8  # smallest ladder page that fits
    assert spec["key"]

    big = _job(2, "stream:n_mem_ops=600,seed=2")  # 601 > 8 pages
    sched.submit(big)
    assert big.state == "QUARANTINED"
    assert big.detail["type"] == "CapacityError"

    sched.submit(_job(3, WINDOW_SYNTH.format(3)))
    with pytest.raises(QueueFull):
        sched.submit(_job(4, WINDOW_SYNTH.format(4)))

    # spawn=False: ticking must not fork anything nor mark progress
    assert sched.tick() is False
    assert sched.pending_work()
    s = sched.stats()
    assert s["workers"] == {"live": 0, "max": 3, "spawned": 0,
                            "coordinator_adopted": False}
    assert s["dispatched"] == 0
    assert s["slots"]["total"] == 3 and s["slots"]["buckets"] == []

    cancelled = sched.cancel("j000003")
    assert cancelled.state == "CANCELLED"
    assert sched.drain() == 1  # the one job still queued
    sched.journal.close()


# ---- kill matrix (real processes, real SIGKILL, concurrent TCP) ----------


def _write_cfg(tmp_path):
    p = str(tmp_path / "cfg.json")
    with open(p, "w") as f:
        f.write(_cfg().to_json())
    return p


def _spawn_frontend(tmp_path, tag, extra=()):
    cfg_path = _write_cfg(tmp_path)
    err_path = str(tmp_path / f"{tag}.stderr")
    argv = [sys.executable, "-m", "primesim_tpu.cli", "serve", cfg_path,
            "--state-dir", str(tmp_path / "state"),
            "--tcp", "127.0.0.1:0",
            "--pool-dir", str(tmp_path / "pool"),
            "--workers", "2", "--chunk-steps", "16",
            "--lease-ttl", "2.0", "--quota", "100",
            "--idle-exit", "20", *extra]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=open(err_path, "w"))
    deadline = time.time() + 180
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"front-end {tag} died at startup: "
                + open(err_path).read()[-2000:]
            )
        m = re.search(r"serve: listening on (\S+)",
                      open(err_path).read())
        if m:
            return proc, m.group(1)
        time.sleep(0.1)
    raise AssertionError(f"front-end {tag} never became ready")


def _worker_pids(pool_sock):
    """Pool-worker processes attached to this campaign's socket, found
    the way an operator would: /proc cmdline scan (no psutil dep)."""
    pids = []
    for p in os.listdir("/proc"):
        if not p.isdigit() or int(p) == os.getpid():
            continue
        try:
            with open(f"/proc/{p}/cmdline", "rb") as f:
                parts = f.read().decode(errors="replace").split("\x00")
        except OSError:
            continue
        if "worker" in parts and pool_sock in parts:
            pids.append(int(p))
    return sorted(pids)


def _kill_quietly(pid, sig=signal.SIGKILL):
    try:
        os.kill(pid, sig)
    except (OSError, ProcessLookupError):
        pass


@pytest.mark.slow
@pytest.mark.parametrize(
    "mode", ["frontend", "coordinator", "worker", "frontend_worker"])
def test_unified_kill9_matrix(tmp_path, mode):
    """The unified-serving acceptance property: kill -9 of ANY process
    in the stack — front-end, coordinator, worker, or front-end+worker
    together — loses no ACKed job. Two concurrent TCP clients submit;
    after the kill (and, for front-end kills, a standby takeover on the
    same state/pool dirs) every job reaches DONE bit-exact vs a solo
    Engine run, and the durable journals show the failover happened."""
    specs = [KILL_SYNTH.format(i) for i in range(4)]
    pool_dir = str(tmp_path / "pool")
    pool_sock = os.path.join(pool_dir, "pool.sock")
    pid_path = os.path.join(pool_dir, "coordinator.pid")
    proc, target = _spawn_frontend(tmp_path, "fe1")
    live = [proc]
    try:
        # two concurrent TCP clients, two submits each — every returned
        # job_id is an ACK (durably journaled before the reply)
        ids = [None] * 4
        errs = []

        def client_thread(k):
            try:
                cli = ServeClient(target, timeout_s=60.0)
                for i in (k, k + 2):
                    ids[i] = cli.submit(
                        synth=specs[i], client=f"tenant{k}")["job_id"]
            except Exception as e:  # surfaced after join
                errs.append(e)

        threads = [threading.Thread(target=client_thread, args=(k,))
                   for k in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert all(ids), ids

        cli = ServeClient(target, timeout_s=60.0)
        deadline = time.time() + 300
        while time.time() < deadline:
            if any(j["state"] == "RUNNING" for j in cli.status()):
                break
            time.sleep(0.2)
        else:
            pytest.fail("no job ever started running")

        if mode in ("worker", "frontend_worker"):
            wdeadline = time.time() + 120
            wpids = _worker_pids(pool_sock)
            while time.time() < wdeadline and not wpids:
                time.sleep(0.2)
                wpids = _worker_pids(pool_sock)
            assert wpids, "no pool-worker process appeared"
            _kill_quietly(wpids[0])
        if mode == "coordinator":
            coord_pid = int(open(pid_path).read())
            _kill_quietly(coord_pid)
        if mode in ("frontend", "frontend_worker"):
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=60)
            # standby takeover: same state dir, same pool dir, new port;
            # the coordinator (and its leases) outlived the front-end
            proc2, target = _spawn_frontend(tmp_path, "fe2")
            live.append(proc2)
            cli = ServeClient(target, timeout_s=60.0)

        results = {i: cli.wait(i, timeout_s=420.0) for i in ids}
        for spec, i in zip(specs, ids):
            assert results[i]["state"] == "DONE", (mode, i, results[i])
            cyc, ctr = _solo_result(_cfg(), spec)
            assert results[i]["result"]["core_cycles"] == cyc
            assert results[i]["result"]["counters"] == ctr

        # let the surviving front-end drain out via --idle-exit
        rc = live[-1].wait(timeout=180)
        assert rc == 0

        # failover evidence in the durable artifacts
        pool_recs, _ = JobJournal(pool_dir).replay()
        if mode == "coordinator":
            # a fresh coordinator (empty ledger) journals no recovery
            # note; the restarted one replays the units and says so
            recovers = [r for r in pool_recs if r.get("t") == "note"
                        and "pool recovered" in r.get("msg", "")
                        and "'ledger_records': 0" not in r.get("msg", "")]
            assert recovers, "no coordinator restart journaled"
            assert os.path.exists(pid_path) is False or \
                int(open(pid_path).read()) != coord_pid
        if mode in ("worker", "frontend_worker"):
            assert any(r.get("t") == "expire" for r in pool_recs), \
                "worker kill never surfaced as a lease expiry"
        if mode in ("frontend", "frontend_worker"):
            serve_recs, _ = JobJournal(str(tmp_path / "state")).replay()
            assert any(r.get("t") == "note"
                       and "adopted live coordinator" in r.get("msg", "")
                       for r in serve_recs), \
                "standby never journaled the coordinator adoption"
    finally:
        for p in live:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        try:
            _kill_quietly(int(open(pid_path).read()), signal.SIGTERM)
        except (OSError, ValueError):
            pass
        for pid in _worker_pids(pool_sock):
            _kill_quietly(pid, signal.SIGTERM)
