"""Windowed streaming ingest (SURVEY.md §2 #8 / §7 bounded-buffer
hand-off): StreamEngine must be BIT-EXACT with the preloaded Engine —
cycles, pointers-consumed, every counter, and the full machine state
including LRU stamps — for any window size, because the device loop's
per-step exit fires before a starved core could diverge an arbitration.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.ingest.stream import StreamEngine
from primesim_tpu.sim.engine import Engine
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import Trace, fold_ins


def assert_stream_matches_preloaded(cfg, trace, window_events):
    full = Engine(cfg, trace, chunk_steps=32)
    full.run()
    s = StreamEngine(cfg, trace, window_events=window_events)
    s.run()
    np.testing.assert_array_equal(s.cycles, full.cycles, err_msg="cycles")
    fc = full.counters
    for k, v in s.counters.items():
        np.testing.assert_array_equal(v, fc[k], err_msg=f"counter {k}")
    # full machine state, LRU stamps included (exactness claim): compare
    # every field except (a) the window-relative trace pointers, (b) the
    # EPOCH-relative clocks (rebase schedules differ between the fused and
    # streaming loops; absolute cycles are compared above via the property,
    # and quantum_end/barrier_time shift with the same epoch), and (c) the
    # step counter: the fused loop rounds up to whole chunks, executing
    # trailing EMPTY steps after completion (no retires, no state writes),
    # while the streaming loop exits exactly at completion
    for f in s.state._fields:
        if f in (
            "ptr", "cycles", "quantum_end", "barrier_time", "step",
            "link_free", "dram_free",  # epoch-relative like cycles
        ):
            continue
        sv, fv = getattr(s.state, f), getattr(full.state, f)
        if hasattr(sv, "_fields"):  # nested pytree (TimingKnobs)
            for kf in sv._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(sv, kf)),
                    np.asarray(getattr(fv, kf)),
                    err_msg=f"{f}.{kf}",
                )
            continue
        np.testing.assert_array_equal(
            np.asarray(sv), np.asarray(fv), err_msg=f
        )
    # total events consumed must equal the real per-core stream lengths
    np.testing.assert_array_equal(
        s.cursor, np.asarray(trace.lengths, dtype=np.int64) - 1
    )


@pytest.mark.parametrize("window", [4, 16, 64])
def test_stream_bit_exact_memory_workload(window):
    cfg = small_test_config(8, n_banks=4, quantum=300)
    assert_stream_matches_preloaded(
        cfg, synth.false_sharing(8, n_mem_ops=40, seed=81), window
    )


def test_stream_bit_exact_folded_local_runs():
    cfg = small_test_config(8, n_banks=4, local_run_len=4)
    tr = fold_ins(synth.fft_like(8, n_phases=2, points_per_core=12, seed=82))
    assert_stream_matches_preloaded(cfg, tr, window_events=8)


@pytest.mark.parametrize("gen_seed", [("lock", 83), ("barrier", 84)])
def test_stream_bit_exact_sync(gen_seed):
    # frozen barrier waiters and spinning lock lanes must survive window
    # boundaries (their un-retired event re-enters the next window)
    gen, seed = gen_seed
    cfg = small_test_config(8, n_banks=4, quantum=200)
    tr = (
        synth.lock_contention(8, n_critical=8, seed=seed)
        if gen == "lock"
        else synth.barrier_phases(8, n_phases=3, seed=seed)
    )
    assert_stream_matches_preloaded(cfg, tr, window_events=8)


def test_stream_uneven_core_lengths():
    # cores exhaust their streams at very different times; starved-exit
    # must not stall finished cores or starve long ones
    from primesim_tpu.trace.format import EV_INS, EV_LD, from_event_lists

    cfg = small_test_config(4, n_banks=4)
    tr = from_event_lists(
        [
            [(EV_LD, 4, i * 64) for i in range(50)],
            [(EV_INS, 10, 0), (EV_LD, 4, 7 * 64)],
            [],
            [(EV_LD, 4, i * 64) for i in range(23)],
        ]
    )
    assert_stream_matches_preloaded(cfg, tr, window_events=5)


def test_stream_mmap_roundtrip(tmp_path):
    # mmapped on-disk v4 trace through the streaming engine: host memory
    # stays O(window), results identical to the in-memory run
    cfg = small_test_config(8, n_banks=4)
    tr = synth.uniform_random(8, n_mem_ops=60, seed=85)
    line_tr = Trace(
        tr.line_events(cfg.line_bits), tr.lengths,
        line_addressed=True, line_bits=cfg.line_bits,
    )
    p = str(tmp_path / "big.ptpu")
    line_tr.save(p)
    mm = Trace.load(p, mmap=True)
    assert isinstance(mm.events, np.memmap) and mm.line_addressed
    assert_stream_matches_preloaded(cfg, mm, window_events=16)


def test_stream_rejects_undersized_window():
    cfg = small_test_config(4, local_run_len=8)
    with pytest.raises(ValueError, match="window_events"):
        StreamEngine(cfg, synth.stream(4, n_mem_ops=4), window_events=4)


def test_cli_stream_window(tmp_path, capsys):
    import json

    from primesim_tpu.cli import main

    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    tr_path = str(tmp_path / "t.ptpu")
    synth.false_sharing(8, n_mem_ops=30, seed=86).save(tr_path)
    rc = main(
        ["run", cfg_path, "--trace", tr_path, "--mmap",
         "--stream-window", "16"]
    )
    assert rc == 0
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # streamed result must equal the preloaded CLI run on the same trace
    rc = main(["run", cfg_path, "--trace", tr_path])
    assert rc == 0
    d2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["detail"]["instructions"] == d2["detail"]["instructions"]
    assert d["detail"]["max_core_cycles"] == d2["detail"]["max_core_cycles"]
    assert d["detail"]["noc_msgs"] == d2["detail"]["noc_msgs"]
