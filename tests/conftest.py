"""Test environment: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the single-host stand-in for multi-chip TPU (SURVEY.md §4d): all
sharding/shard_map logic is exercised on 8 virtual CPU devices; the driver
separately dry-run-compiles the multi-chip path via __graft_entry__.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
