"""Test environment: force an 8-device virtual CPU mesh.

This is the single-host stand-in for multi-chip TPU (SURVEY.md §4d): all
sharding/shard_map logic is exercised on 8 virtual CPU devices; the driver
separately dry-run-compiles the multi-chip path via __graft_entry__.

NOTE: this image's sitecustomize pre-imports jax with the `axon` TPU
platform at interpreter startup, so env vars alone are too late — we must
set XLA_FLAGS (read lazily at CPU-client creation) and then switch the
platform through jax.config before any backend is touched.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if len(jax.devices()) != 8:
    raise RuntimeError(
        f"tests need an 8-device virtual CPU mesh, got {jax.devices()}; "
        f"XLA_FLAGS={os.environ.get('XLA_FLAGS')!r} already carried a "
        "conflicting xla_force_host_platform_device_count?"
    )
