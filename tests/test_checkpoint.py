"""Checkpoint/resume bit-exactness (SURVEY.md §5.4) + xml_compat loader."""

import os

import numpy as np
import pytest

from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.sim.engine import Engine
from primesim_tpu.trace import synth

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _full_state_equal(a, b):
    for k in a._fields:
        va, vb = getattr(a, k), getattr(b, k)
        if hasattr(va, "_fields"):  # nested pytree (TimingKnobs)
            _full_state_equal(va, vb)
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=k
        )


@pytest.mark.parametrize("gen", ["fft_like", "lock_contention"])
def test_checkpoint_resume_bit_exact(tmp_path, gen):
    cfg = small_test_config(8, n_banks=4, quantum=200)
    tr = (
        synth.fft_like(8, n_phases=2, points_per_core=12, seed=41)
        if gen == "fft_like"
        else synth.lock_contention(8, n_critical=8, seed=42)
    )
    ckpt = str(tmp_path / "mid.npz")

    # uninterrupted reference run
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()
    ref_counters = {k: v.copy() for k, v in ref.counters.items()}

    # run A steps -> save -> fresh engine -> load -> finish
    a = Engine(cfg, tr, chunk_steps=16)
    a.run_steps(48)
    assert not a.done()  # checkpoint taken mid-run, not at the end
    a.save_checkpoint(ckpt)

    b = Engine(cfg, tr, chunk_steps=16)
    b.load_checkpoint(ckpt)
    b.run()

    np.testing.assert_array_equal(b.cycles, ref.cycles)
    _full_state_equal(b.state, ref.state)
    bc = b.counters
    for k, v in ref_counters.items():
        np.testing.assert_array_equal(bc[k], v, err_msg=k)


def test_stream_checkpoint_resume_bit_exact(tmp_path):
    # VERDICT r4 #8: the billion-event runs streaming exists for need
    # resume. run_events pauses at a window boundary (the consistent
    # cut); save -> fresh StreamEngine -> load -> finish must be
    # bit-exact with an uninterrupted streamed run AND the preloaded
    # engine.
    from primesim_tpu.ingest.stream import StreamEngine

    cfg = small_test_config(8, n_banks=4, quantum=200)
    tr = synth.false_sharing(8, n_mem_ops=40, seed=44)
    ckpt = str(tmp_path / "stream.npz")

    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    a = StreamEngine(cfg, tr, window_events=8)
    finished = a.run_events(80)
    assert not finished  # mid-stream cut
    a.save_checkpoint(ckpt)

    b = StreamEngine(cfg, tr, window_events=8)
    b.load_checkpoint(ckpt)
    b.run()
    np.testing.assert_array_equal(b.cycles, ref.cycles)
    bc, rc = b.counters, ref.counters
    for k, v in rc.items():
        np.testing.assert_array_equal(bc[k], v, err_msg=k)

    # a plain Engine must refuse a streaming checkpoint
    c = Engine(cfg, tr, chunk_steps=16)
    with pytest.raises(ValueError, match="[Ss]tream"):
        c.load_checkpoint(ckpt)
    # and window geometry is part of the resume contract
    d = StreamEngine(cfg, tr, window_events=16)
    with pytest.raises(ValueError, match="window"):
        d.load_checkpoint(ckpt)


def test_checkpoint_resume_multichip_mesh(tmp_path):
    # load_checkpoint must restore the multi-chip sharding layout, not
    # materialize the state unsharded on one device
    from primesim_tpu.parallel.sharding import tile_mesh

    cfg = small_test_config(8, n_banks=8)
    tr = synth.false_sharing(8, n_mem_ops=24, seed=44)
    mesh = tile_mesh(8)

    ref = Engine(cfg, tr, chunk_steps=8, mesh=mesh)
    ref.run()

    a = Engine(cfg, tr, chunk_steps=8, mesh=mesh)
    a.run_steps(16)
    ckpt = str(tmp_path / "mesh.npz")
    a.save_checkpoint(ckpt)
    b = Engine(cfg, tr, chunk_steps=8, mesh=mesh)
    b.load_checkpoint(ckpt)
    assert len(b.state.cycles.sharding.device_set) == 8  # re-sharded
    b.run()
    np.testing.assert_array_equal(b.cycles, ref.cycles)
    _full_state_equal(b.state, ref.state)


def test_checkpoint_rejects_mismatches(tmp_path):
    cfg = small_test_config(4)
    tr = synth.stream(4, n_mem_ops=10, seed=43)
    e = Engine(cfg, tr, chunk_steps=8)
    e.run_steps(8)
    ckpt = str(tmp_path / "c.npz")
    e.save_checkpoint(ckpt)

    other_cfg = small_test_config(4, quantum=777)
    with pytest.raises(ValueError, match="config does not match"):
        Engine(other_cfg, tr, chunk_steps=8).load_checkpoint(ckpt)
    other_tr = synth.stream(4, n_mem_ops=10, seed=99)
    with pytest.raises(ValueError, match="trace does not match"):
        Engine(cfg, other_tr, chunk_steps=8).load_checkpoint(ckpt)


def test_fleet_checkpoint_resume_bit_exact(tmp_path):
    # fleet snapshots carry the BATCHED state plus per-element 64-bit
    # cycle bases / counter accumulators; resume must be bit-exact per
    # element against an uninterrupted fleet run
    from primesim_tpu.sim.fleet import FleetEngine

    cfg = small_test_config(8, n_banks=4, quantum=200)
    traces = [
        synth.fft_like(8, n_phases=2, points_per_core=12, seed=45),
        synth.lock_contention(8, n_critical=8, seed=46),
        synth.false_sharing(8, n_mem_ops=40, seed=47),
    ]
    overrides = [{}, {"llc_lat": 25, "quantum": 150}, {"dram_lat": 140}]
    ckpt = str(tmp_path / "fleet.npz")

    ref = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    ref.run()
    ref_counters = {k: v.copy() for k, v in ref.counters.items()}

    a = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    a.run_steps(48)
    assert not a.done()  # mid-run cut
    a.save_checkpoint(ckpt)

    b = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    b.load_checkpoint(ckpt)
    b.run()

    np.testing.assert_array_equal(b.cycles, ref.cycles)
    _full_state_equal(b.state, ref.state)
    bc = b.counters
    for k, v in ref_counters.items():
        np.testing.assert_array_equal(bc[k], v, err_msg=k)


def test_fleet_checkpoint_rejects_mismatches(tmp_path):
    from primesim_tpu.sim.fleet import FleetEngine

    cfg = small_test_config(4, n_banks=4)
    traces = [
        synth.stream(4, n_mem_ops=20, seed=48),
        synth.uniform_random(4, n_mem_ops=20, seed=49),
    ]
    fl = FleetEngine(cfg, traces, [{}, {"llc_lat": 20}], chunk_steps=8)
    fl.run_steps(8)
    ckpt = str(tmp_path / "fleet.npz")
    fl.save_checkpoint(ckpt)

    # a plain Engine must refuse a fleet checkpoint, and vice versa
    with pytest.raises(ValueError, match="[Ff]leet"):
        Engine(cfg, traces[0], chunk_steps=8).load_checkpoint(ckpt)
    solo_ckpt = str(tmp_path / "solo.npz")
    e = Engine(cfg, traces[0], chunk_steps=8)
    e.run_steps(8)
    e.save_checkpoint(solo_ckpt)
    with pytest.raises(ValueError, match="fleet checkpoint"):
        FleetEngine(cfg, traces, chunk_steps=8).load_checkpoint(solo_ckpt)

    # element configs (overrides included) and traces are part of the
    # resume contract — the batch axis is positional
    with pytest.raises(ValueError, match="configs do not match"):
        FleetEngine(cfg, traces, [{}, {"llc_lat": 99}],
                    chunk_steps=8).load_checkpoint(ckpt)
    with pytest.raises(ValueError, match="traces do not match"):
        FleetEngine(
            cfg, list(reversed(traces)), [{}, {"llc_lat": 20}],
            chunk_steps=8,
        ).load_checkpoint(ckpt)


def test_crash_mid_write_never_replaces_good_snapshot(tmp_path, monkeypatch):
    # DESIGN.md §10 durability contract: saves go tmp + fsync +
    # os.replace, so a crash mid-write leaves the previous snapshot
    # byte-identical (and no .tmp litter)
    from primesim_tpu.sim import checkpoint as ckpt_mod

    cfg = small_test_config(8, n_banks=4, quantum=200)
    tr = synth.fft_like(8, n_phases=2, points_per_core=12, seed=41)
    eng = Engine(cfg, tr, chunk_steps=16)
    eng.run_steps(16)
    path = tmp_path / "c.npz"
    eng.save_checkpoint(str(path))
    good = path.read_bytes()

    eng.run_steps(16)

    def dies_mid_write(f, **arrays):
        f.write(b"torn partial npz bytes")
        raise OSError("simulated crash mid-write")

    monkeypatch.setattr(ckpt_mod.np, "savez_compressed", dies_mid_write)
    with pytest.raises(OSError, match="simulated crash"):
        eng.save_checkpoint(str(path))
    monkeypatch.undo()

    assert path.read_bytes() == good  # untouched by the torn write
    assert not (tmp_path / "c.npz.tmp").exists()  # tmp cleaned up
    fresh = Engine(cfg, tr, chunk_steps=16)
    fresh.load_checkpoint(str(path))  # and it still loads + verifies
    assert fresh.steps_run == 16


def test_accumulator_guard_rejects_oversized_chunks():
    from primesim_tpu.trace.format import EV_INS, from_event_lists

    cfg = small_test_config(2, n_banks=2)
    tr = from_event_lists([[(EV_INS, 1 << 22, 0)], []])
    with pytest.raises(ValueError, match="accumulator"):
        Engine(cfg, tr, chunk_steps=512)
    Engine(cfg, tr, chunk_steps=64)  # small chunks stay under the guard


# ------------------------------------------------------------- xml_compat


def test_xml_compat_matches_json_rung1():
    from primesim_tpu.config.xml_compat import load_xml

    cfg = load_xml(os.path.join(REPO, "configs", "example_prime.xml"))
    with open(os.path.join(REPO, "configs", "rung1_64core_fft.json")) as f:
        want = MachineConfig.from_json(f.read())
    # the XML example mirrors rung 1 except the local_run_len tuning knob
    import dataclasses

    assert cfg == dataclasses.replace(want, local_run_len=0)


def test_xml_compat_aliases_and_errors(tmp_path):
    from primesim_tpu.config.xml_compat import load_xml

    p = tmp_path / "alias.xml"
    p.write_text(
        """<sim><sys>
        <n_cores>8</n_cores>
        <quantum>500</quantum>
        <dram_latency>90</dram_latency>
        <network><x_dimension>2</x_dimension><y_dimension>2</y_dimension>
        </network>
        <cache level="1"><size>1024</size><associativity>2</associativity>
          <line_size>64</line_size><latency>2</latency></cache>
        <cache level="2" shared="yes" num_banks="4"><size>8192</size>
          <num_ways>4</num_ways><line_size>64</line_size>
          <access_time>11</access_time></cache>
        </sys></sim>"""
    )
    cfg = load_xml(str(p))
    assert cfg.n_cores == 8 and cfg.quantum == 500 and cfg.dram_lat == 90
    assert cfg.l1.ways == 2 and cfg.llc.latency == 11 and cfg.n_banks == 4

    bad = tmp_path / "bad.xml"
    bad.write_text("<sim><sys><num_cores>8</num_cores></sys></sim>")
    with pytest.raises(ValueError, match="cache"):
        load_xml(str(bad))


def test_cli_accepts_xml_config(capsys):
    import json

    from primesim_tpu.cli import main

    xml = os.path.join(REPO, "configs", "example_prime.xml")
    assert main(["info", xml]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["n_cores"] == 64 and d["llc"]["size"] == 262144
