"""Deterministic fault-injection subsystem (DESIGN.md §12).

Covers the whole fault contract: faults-off bit-exactness, seeded
determinism (same schedule => identical fault counters, solo vs
fleet-vmapped), dead-core barrier non-deadlock + directory scrub, NoC
reroute latency accounting against the scalar reference model, SECDED
ECC corrected/DUE counters, and the chaos-hardened supervisor
(fault mid-run + preempt + checkpoint + --resume, bit-exact).
"""

import dataclasses
import json
import os
import signal
import types

import numpy as np
import pytest

import jax.numpy as jnp

from primesim_tpu.config.machine import (
    FAULT_CORE_FAILSTOP,
    FAULT_LINK_DEGRADE,
    FAULT_LINK_FAIL,
    FaultConfigError,
    small_test_config,
)
from primesim_tpu.faults.inject import leg_fault_penalty
from primesim_tpu.faults.prng import (
    prob_threshold,
    site_hash,
    site_hash_np,
)
from primesim_tpu.faults.schedule import (
    FaultSchedule,
    fault_state_from_config,
    load_schedule,
    schedule_from_dict,
)
from primesim_tpu.noc import mesh
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.fleet import FleetEngine, apply_overrides
from primesim_tpu.sim.supervisor import Preempted, RunSupervisor
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_END

FAULT_COUNTERS = ("core_failstops", "noc_reroutes", "ecc_corrected", "ecc_due")


def _cfg(**kw):
    return small_test_config(8, n_banks=4, quantum=200, **kw)


def _trace(n_mem_ops=96, seed=3):
    return synth.uniform_random(
        8, n_mem_ops=n_mem_ops, shared_frac=0.4, seed=seed
    )


def _armed(cfg=None, **kw):
    """cfg with faults enabled and the given fault knobs installed."""
    cfg = cfg or _cfg()
    kw.setdefault("max_fault_events", max(1, len(kw.get("fault_events", ()))))
    return dataclasses.replace(cfg, faults_enabled=True, **kw)


def _run(cfg, tr, **kw):
    eng = Engine(cfg, tr, **kw)
    eng.run()
    return eng


def _same_results(a, b):
    np.testing.assert_array_equal(a.cycles, b.cycles)
    bc = b.counters
    for k, v in a.counters.items():
        np.testing.assert_array_equal(v, bc[k], err_msg=k)


# ---- counter-based PRNG ---------------------------------------------------


def test_site_hash_matches_numpy_twin():
    steps = np.arange(0, 300, 7, dtype=np.int64)
    sites = np.arange(40, dtype=np.int64)
    dev = np.asarray(
        site_hash(
            jnp.uint32(0xDEADBEEF),
            jnp.asarray(steps)[:, None],
            jnp.asarray(sites)[None, :],
            salt=17,
        )
    )
    host = site_hash_np(0xDEADBEEF, steps[:, None], sites[None, :], salt=17)
    np.testing.assert_array_equal(dev, host.astype(dev.dtype))


def test_site_hash_is_decorrelated_across_inputs():
    h = np.asarray(
        site_hash(jnp.uint32(5), jnp.arange(64)[:, None], jnp.arange(8)[None])
    )
    assert len(np.unique(h)) == h.size  # no collisions on a small grid


def test_prob_threshold_endpoints():
    assert int(prob_threshold(0.0)) == 0
    assert int(prob_threshold(1.0)) == 0xFFFFFFFF
    assert 0 < int(prob_threshold(1e-6)) < int(prob_threshold(1e-3))


# ---- faults-off / empty-schedule bit-exactness ----------------------------


def test_faults_off_state_has_fault_pytree_but_never_reads_it():
    eng = _run(_cfg(), _trace())
    assert int(np.asarray(eng.state.faults.core_dead).sum()) == 0
    for k in FAULT_COUNTERS:
        assert int(eng.counters[k].sum()) == 0, k


def test_empty_schedule_is_bit_exact_vs_faults_off():
    tr = _trace()
    base = _run(_cfg(), tr)
    armed = _run(_armed(fault_seed=7), tr)
    _same_results(armed, base)
    np.testing.assert_array_equal(
        np.asarray(armed.state.l1), np.asarray(base.state.l1)
    )
    np.testing.assert_array_equal(
        np.asarray(armed.state.dirm), np.asarray(base.state.dirm)
    )


def test_fault_seed_is_traced_not_part_of_jit_key():
    cfg = _armed(fault_seed=1, fault_flip_l1=1e-6)
    for seed in (2, 3, 999):
        ov = apply_overrides(cfg, {"fault_seed": seed})
        assert ov.fault_seed == seed
        # identical normalized key => `sweep --vary fault_seed` reuses ONE
        # compiled program (the no-recompile acceptance criterion)
        assert ov.timing_normalized() == cfg.timing_normalized()


# ---- core fail-stop -------------------------------------------------------


def test_failstop_completes_without_deadlock_and_counts_once():
    tr = _trace()
    cfg = _armed(fault_events=((5, FAULT_CORE_FAILSTOP, 3, 0),))
    eng = _run(cfg, tr)
    assert eng.done()
    fs = eng.counters["core_failstops"]
    assert fs[3] == 1 and fs.sum() == 1
    assert bool(eng.done_mask()[3]) and not bool(eng.live_mask()[3])
    # the dead core retires nothing after step 5; the others all finish
    live_done = eng._event_types_at_ptr() == EV_END
    assert live_done[np.arange(8) != 3].all()
    eng.verify_invariants()  # directory scrub left a consistent machine


def test_failstop_with_barrier_trace_releases_peers():
    # cores hit barriers every phase; killing one AFTER its first arrival
    # must not deadlock the quantum loop (dead cores leave the barrier's
    # quantum accounting)
    tr = synth.barrier_phases(8, n_phases=3, work_per_phase=8, seed=5)
    cfg = _armed(fault_events=((2, FAULT_CORE_FAILSTOP, 6, 0),))
    eng = _run(cfg, tr)
    assert eng.done()
    assert eng.counters["core_failstops"].sum() == 1
    eng.verify_invariants()


def test_failstop_dead_policy_writeback_vs_drop():
    tr = _trace(n_mem_ops=128)
    ev = ((20, FAULT_CORE_FAILSTOP, 2, 0),)
    wb = _run(_armed(fault_events=ev, fault_dead_policy="writeback"), tr)
    dr = _run(_armed(fault_events=ev, fault_dead_policy="drop"), tr)
    assert wb.done() and dr.done()
    wb.verify_invariants()
    dr.verify_invariants()
    # writeback bills the dead owner for flushing its dirty lines; drop
    # discards them (no writeback traffic for the dead core's lines)
    assert (
        wb.counters["l1_writebacks"].sum() >= dr.counters["l1_writebacks"].sum()
    )


def test_same_schedule_same_seed_is_deterministic():
    tr = _trace()
    cfg = _armed(
        fault_events=((10, FAULT_CORE_FAILSTOP, 1, 0),),
        fault_flip_l1=1.0,
        fault_due_rate=0.5,
        fault_seed=42,
    )
    _same_results(_run(cfg, tr), _run(cfg, tr))


# ---- link failure / degradation ------------------------------------------


# link 0 = tile 0 eastward: the first hop of every tile-0 -> tile-1
# message on the 2x2 test mesh, so baseline traffic definitely crosses it
BUSY_LINK = 0


def test_link_fail_reroutes_and_adds_latency():
    tr = _trace(n_mem_ops=128)
    base = _run(_cfg(), tr)
    cfg = _armed(fault_events=((0, FAULT_LINK_FAIL, BUSY_LINK, 0),))
    eng = _run(cfg, tr)
    assert eng.done()
    rr = int(eng.counters["noc_reroutes"].sum())
    assert rr > 0
    # detours cost hops and cycles in aggregate (per-core deltas are NOT
    # monotone: slower messages legitimately reorder arbitration races)
    assert eng.counters["noc_hops"].sum() > base.counters["noc_hops"].sum()
    assert eng.cycles.sum() > base.cycles.sum()
    assert eng.cycles.max() >= base.cycles.max()


def test_link_degrade_adds_latency_without_reroutes():
    tr = _trace(n_mem_ops=128)
    base = _run(_cfg(), tr)
    cfg = _armed(fault_events=((0, FAULT_LINK_DEGRADE, BUSY_LINK, 9),))
    eng = _run(cfg, tr)
    assert eng.done()
    # degraded links are slower but never detoured
    assert int(eng.counters["noc_reroutes"].sum()) == 0
    assert eng.cycles.sum() > base.cycles.sum()


def test_leg_penalty_matches_scalar_reference_model():
    from primesim_tpu.config.machine import NocConfig

    cfg = small_test_config(
        16, noc=NocConfig(mesh_x=4, mesh_y=4, link_lat=1, router_lat=2)
    )
    nl = cfg.n_tiles * 4
    rng = np.random.default_rng(7)
    link_dead = (rng.random(nl) < 0.2).astype(np.int32)
    link_extra = rng.integers(0, 6, nl).astype(np.int32) * (1 - link_dead)
    fs = fault_state_from_config(
        dataclasses.replace(cfg, faults_enabled=True, max_fault_events=1)
    )._replace(
        link_dead=jnp.asarray(link_dead), link_extra=jnp.asarray(link_extra)
    )
    kn = types.SimpleNamespace(
        link_lat=jnp.int32(cfg.noc.link_lat),
        router_lat=jnp.int32(cfg.noc.router_lat),
    )
    tiles = np.arange(cfg.n_tiles, dtype=np.int32)
    a = np.repeat(tiles, cfg.n_tiles)
    b = np.tile(tiles, cfg.n_tiles)
    lat, hops, rer = leg_fault_penalty(cfg, fs, kn, jnp.asarray(a), jnp.asarray(b))
    for i in range(a.size):
        ref = mesh.detour_stats(
            int(a[i]), int(b[i]), cfg.noc.mesh_x, link_dead, link_extra,
            cfg.noc.link_lat, cfg.noc.router_lat,
        )
        assert (int(lat[i]), int(hops[i]), int(rer[i])) == ref, (a[i], b[i])


# ---- ECC (SECDED) ---------------------------------------------------------


def test_ecc_corrected_has_counters_but_zero_timing_effect():
    tr = _trace()
    base = _run(_cfg(), tr)
    eng = _run(_armed(fault_flip_l1=1.0, fault_flip_llc=1.0, fault_seed=9), tr)
    assert int(eng.counters["ecc_corrected"].sum()) > 0
    assert int(eng.counters["ecc_due"].sum()) == 0
    # SECDED corrects in-line: counted, never architecturally visible
    np.testing.assert_array_equal(eng.cycles, base.cycles)
    for k in ("instructions", "noc_msgs", "llc_misses"):
        np.testing.assert_array_equal(eng.counters[k], base.counters[k])


def test_ecc_due_counted_and_seed_dependent():
    tr = _trace()
    cfg = _armed(fault_flip_l1=1.0, fault_due_rate=0.5, fault_seed=1)
    eng = _run(cfg, tr)
    due = int(eng.counters["ecc_due"].sum())
    corr = int(eng.counters["ecc_corrected"].sum())
    assert due > 0 and corr > 0
    # without escalation a DUE is counted but not fatal
    assert int(eng.counters["core_failstops"].sum()) == 0
    again = _run(cfg, tr)
    np.testing.assert_array_equal(
        eng.counters["ecc_due"], again.counters["ecc_due"]
    )


def test_due_failstop_escalation_kills_cores():
    tr = _trace()
    cfg = _armed(
        fault_flip_l1=1.0,
        fault_due_rate=1.0,
        fault_due_failstop=True,
        fault_seed=2,
    )
    eng = _run(cfg, tr)
    assert eng.done()
    # every core machine-checks on its first (certain) L1 DUE
    assert int(eng.counters["core_failstops"].sum()) == 8


# ---- solo vs fleet determinism -------------------------------------------


def test_fault_counters_identical_solo_vs_fleet():
    # different trace LENGTHS on purpose: the early-finishing element
    # keeps stepping inside the batch until the fleet drains, and must
    # accrue NO extra fault counts relative to its solo run
    tra, trb = _trace(n_mem_ops=48, seed=1), _trace(n_mem_ops=128, seed=2)
    cfg = _armed(
        fault_events=((15, FAULT_CORE_FAILSTOP, 4, 0),),
        fault_flip_l1=1.0,
        fault_due_rate=0.25,
    )
    fleet = FleetEngine(cfg, [tra, trb], [{"fault_seed": 11}, {"fault_seed": 22}])
    fleet.run()
    for i, (tr, seed) in enumerate(((tra, 11), (trb, 22))):
        solo = _run(dataclasses.replace(cfg, fault_seed=seed), tr)
        np.testing.assert_array_equal(fleet.cycles[i], solo.cycles)
        for k, v in fleet.counters.items():
            np.testing.assert_array_equal(
                v[i], solo.counters[k], err_msg=f"element {i}: {k}"
            )


def test_fleet_fault_seed_sweep_shares_one_jit_key():
    cfg = _armed(fault_flip_l1=1e-4)
    fleet = FleetEngine(
        cfg,
        [_trace(n_mem_ops=32)] * 3,
        [{"fault_seed": s} for s in (1, 2, 3)],
    )
    keys = {c.timing_normalized() for c in fleet.elem_cfgs}
    assert keys == {cfg.timing_normalized()}


# ---- checkpoint / supervisor (chaos mode) --------------------------------


def test_checkpoint_roundtrip_carries_fault_state(tmp_path):
    tr = _trace(n_mem_ops=128)
    cfg = _armed(
        fault_events=((5, FAULT_CORE_FAILSTOP, 0, 0),), fault_flip_l1=1.0
    )
    eng = Engine(cfg, tr, chunk_steps=8)
    eng.run_steps(16)
    path = str(tmp_path / "ck.npz")
    eng.save_checkpoint(path)
    assert int(np.asarray(eng.state.faults.core_dead)[0]) == 1
    other = Engine(cfg, tr, chunk_steps=8)
    other.load_checkpoint(path)
    for k in eng.state.faults._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(other.state.faults, k)),
            np.asarray(getattr(eng.state.faults, k)),
            err_msg=k,
        )
    eng.run()
    other.run()
    _same_results(other, eng)


def test_guard_fail_is_fault_aware_no_false_positive():
    tr = _trace(n_mem_ops=128)
    cfg = _armed(fault_events=((10, FAULT_CORE_FAILSTOP, 5, 0),))
    eng = Engine(cfg, tr, chunk_steps=8)
    sup = RunSupervisor(eng, guard="fail", handle_signals=False)
    sup.run()  # GuardViolation here would mean dead-core false positive
    assert eng.done()
    assert int(eng.counters["core_failstops"].sum()) == 1
    log = "\n".join(sup.log_lines())
    assert "chaos" in log and "core_failstops +1" in log


def test_chaos_preempt_resume_is_bit_exact(tmp_path):
    tr = _trace(n_mem_ops=192)
    cfg = _armed(
        fault_events=(
            (6, FAULT_CORE_FAILSTOP, 7, 0),
            (40, FAULT_LINK_FAIL, 0, 0),
        ),
        fault_flip_l1=1.0,
        fault_due_rate=0.125,
        fault_seed=5,
    )

    ref = Engine(cfg, tr, chunk_steps=8)
    RunSupervisor(ref, guard="fail", handle_signals=False).run()
    assert ref.done()

    def kill_at(chunk):
        def on_chunk(sup):
            if sup.committed == chunk:
                os.kill(os.getpid(), signal.SIGTERM)

        return on_chunk

    eng = Engine(cfg, tr, chunk_steps=8)
    sup = RunSupervisor(
        eng,
        snapshot_dir=str(tmp_path),
        checkpoint_every_chunks=1,
        guard="fail",
        on_chunk=kill_at(2),
    )
    with pytest.raises(Preempted):
        sup.run()

    eng2 = Engine(cfg, tr, chunk_steps=8)
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path), guard="fail")
    assert sup2.resume() is not None
    sup2.run()
    assert eng2.done()
    _same_results(eng2, ref)
    assert "chaos" in "\n".join(sup2.log_lines())


# ---- typed config / schedule errors --------------------------------------


def _expect_error(field=None, **cfg_kw):
    with pytest.raises(FaultConfigError) as ei:
        _armed(**cfg_kw)
    if field:
        assert field in ei.value.location()
    return ei.value


def test_config_validation_rejects_bad_fault_fields():
    _expect_error(fault_events=((5, FAULT_CORE_FAILSTOP, 99, 0),))  # core oob
    _expect_error(fault_events=((-2, FAULT_CORE_FAILSTOP, 1, 0),))  # step < 0
    _expect_error(fault_events=((1, 77, 0, 0),))  # unknown kind
    _expect_error(fault_events=((1, FAULT_LINK_FAIL, 10_000, 0),))  # link oob
    _expect_error(fault_flip_l1=1.5)
    _expect_error(fault_due_rate=-0.1)
    _expect_error(fault_dead_policy="shrug")
    _expect_error(  # more events than the static capacity
        fault_events=((1, FAULT_CORE_FAILSTOP, 0, 0),) * 3, max_fault_events=2
    )


def test_failstop_requires_exact_directory():
    with pytest.raises(FaultConfigError):
        dataclasses.replace(
            small_test_config(64, sharer_group=8),
            faults_enabled=True,
            max_fault_events=1,
            fault_events=((1, FAULT_CORE_FAILSTOP, 0, 0),),
        )


def test_schedule_from_dict_and_typed_errors(tmp_path):
    sched = schedule_from_dict(
        {
            "events": [
                {"step": 4, "kind": "core_failstop", "core": 2},
                {"step": 9, "kind": "link_degrade", "link": 1, "extra": 3},
            ],
            "flip_l1": 1e-6,
            "due_failstop": True,
        }
    )
    assert sched.events == (
        (4, FAULT_CORE_FAILSTOP, 2, 0),
        (9, FAULT_LINK_DEGRADE, 1, 3),
    )
    cfg = sched.apply(_cfg(), seed=3)
    assert cfg.faults_enabled and cfg.fault_seed == 3
    assert cfg.max_fault_events == 2  # rounded to a power of two
    assert cfg.fault_due_failstop

    with pytest.raises(FaultConfigError, match="unknown kind"):
        schedule_from_dict({"events": [{"step": 1, "kind": "meteor"}]})
    with pytest.raises(FaultConfigError, match="missing 'step'"):
        schedule_from_dict({"events": [{"kind": "link_fail", "link": 0}]})
    with pytest.raises(FaultConfigError, match="unknown schedule field"):
        schedule_from_dict({"evnets": []})

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    with pytest.raises(FaultConfigError, match="not valid JSON"):
        load_schedule(str(bad))


def test_schedule_apply_pads_capacity_pow2():
    s = FaultSchedule(events=((1, FAULT_CORE_FAILSTOP, 0, 0),) * 3)
    assert s.apply(_cfg()).max_fault_events == 4
    assert FaultSchedule().apply(_cfg()).max_fault_events == 1


# ---- CLI + report surface -------------------------------------------------


def _write_cli_inputs(tmp_path, schedule):
    cfg_path = tmp_path / "m.json"
    cfg_path.write_text(_cfg().to_json())
    sc_path = tmp_path / "faults.json"
    sc_path.write_text(json.dumps(schedule))
    return str(cfg_path), str(sc_path)


def test_cli_run_with_fault_schedule(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg_path, sc_path = _write_cli_inputs(
        tmp_path,
        {
            "events": [{"step": 5, "kind": "core_failstop", "core": 3}],
            "flip_l1": 1.0,
        },
    )
    rpt = str(tmp_path / "r.txt")
    rc = main(
        [
            "run", cfg_path,
            "--synth", "uniform_random:n_mem_ops=64",
            "--fault-schedule", sc_path,
            "--fault-seed", "7",
            "--report", rpt,
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1])["unit"] == "MIPS"
    text = open(rpt).read()
    assert "FAULTS" in text
    assert "core fail-stops" in text and "dead cores          3" in text
    assert "ECC corrected" in text


def test_cli_fault_seed_requires_armed_config(tmp_path):
    from primesim_tpu.cli import main

    cfg_path, _ = _write_cli_inputs(tmp_path, {})
    with pytest.raises(SystemExit, match="fault-seed"):
        main(
            [
                "run", cfg_path,
                "--synth", "uniform_random:n_mem_ops=16",
                "--fault-seed", "7",
            ]
        )


def test_cli_bad_schedule_is_a_clean_error(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg_path, sc_path = _write_cli_inputs(
        tmp_path, {"events": [{"step": 1, "kind": "meteor"}]}
    )
    rc = main(
        [
            "run", cfg_path,
            "--synth", "uniform_random:n_mem_ops=16",
            "--fault-schedule", sc_path,
        ]
    )
    assert rc == 2
    # typed errors leave the CLI as ONE structured JSON line (serve S2)
    err_line = capsys.readouterr().err.strip().splitlines()[-1]
    err = json.loads(err_line)["error"]
    assert err["type"] == "FaultConfigError"
    assert "meteor" in err["detail"]


def test_cli_faults_reject_streaming_and_golden(tmp_path):
    from primesim_tpu.cli import main

    cfg_path, sc_path = _write_cli_inputs(
        tmp_path, {"events": [{"step": 1, "kind": "link_fail", "link": 0}]}
    )
    base = [
        "run", cfg_path, "--synth", "uniform_random:n_mem_ops=16",
        "--fault-schedule", sc_path,
    ]
    with pytest.raises(SystemExit, match="stream"):
        main(base + ["--stream-window", "64"])
    with pytest.raises(SystemExit, match="golden"):
        main(base + ["--engine", "golden"])


def test_report_has_no_faults_section_when_off():
    from primesim_tpu.stats.report import render_report

    eng = _run(_cfg(), _trace(n_mem_ops=32))
    text = render_report(eng.cfg, eng.counters, eng.cycles)
    assert "FAULTS" not in text
