"""Debug invariants + randomized MESI property tests (SURVEY.md §4b,
DESIGN.md §5).

The invariant checker must (a) hold on every state a legal workload can
reach — driven here by randomized adversarial request streams, heavy
sharing, sync events, tiny caches — and (b) actually DETECT violations
(checked by corrupting states on purpose).
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig, small_test_config
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.validate import check_invariants
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, EV_ST, from_event_lists


def tiny_machine(n_cores=8, **kw):
    # tiny caches maximize evictions/back-invalidations per event
    d = dict(
        n_cores=n_cores,
        n_banks=4,
        l1=CacheConfig(size=256, ways=2, line=64, latency=2),
        llc=CacheConfig(size=1024, ways=2, line=64, latency=9),
        noc=NocConfig(mesh_x=2, mesh_y=2),
        quantum=128,
    )
    d.update(kw)
    return MachineConfig(**d)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_invariants_hold_on_random_streams(seed):
    rng = np.random.default_rng(seed)
    n = 8
    evs = []
    for c in range(n):
        core_evs = []
        for _ in range(60):
            # heavy sharing: 12 hot lines across 4 banks + private tail
            if rng.random() < 0.7:
                line = int(rng.integers(0, 12))
            else:
                line = 100 + c * 8 + int(rng.integers(0, 8))
            t = EV_ST if rng.random() < 0.5 else EV_LD
            core_evs.append((t, 4, line * 64))
        evs.append(core_evs)
    cfg = tiny_machine(n)
    eng = Engine(cfg, from_event_lists(evs), chunk_steps=16)
    eng.run_chunked(debug_invariants=True)  # checks after every chunk
    eng.verify_invariants()


def test_invariants_hold_with_sync_and_contention():
    cfg = tiny_machine(
        8,
        noc=NocConfig(mesh_x=2, mesh_y=2, contention=True, contention_lat=2),
    )
    eng = Engine(
        cfg, synth.lock_contention(8, n_critical=6, seed=9), chunk_steps=16
    )
    eng.run_chunked(debug_invariants=True)
    eng2 = Engine(
        cfg, synth.barrier_phases(8, n_phases=2, seed=10), chunk_steps=16
    )
    eng2.run_chunked(debug_invariants=True)


def test_checker_detects_violations():
    import jax.numpy as jnp

    cfg = small_test_config(4)
    eng = Engine(cfg, synth.false_sharing(4, n_mem_ops=20, seed=11))
    eng.run()
    check_invariants(cfg, eng.state)  # clean state passes

    # owned entry with sharers recorded (fused dirm layout: column
    # (set*W2 + way)*2 holds the tag, +1 the owner, and the sharer words
    # start at llc_meta_width — (bank 0, set 0, way 0) is cols 0/1 and
    # its first sharer word MW+0)
    from primesim_tpu.sim.state import llc_meta_width

    MW = llc_meta_width(cfg)
    bad = eng.state._replace(
        dirm=eng.state.dirm.at[0, 0].set(12345).at[0, 1].set(1)
        .at[0, MW].set(0b11),
    )
    with pytest.raises(AssertionError, match="sharer set"):
        check_invariants(cfg, bad)

    # out-of-range owner
    bad = eng.state._replace(dirm=eng.state.dirm.at[0, 1].set(99))
    with pytest.raises(AssertionError, match="out of range"):
        check_invariants(cfg, bad)

    # duplicate valid LLC tag within a set (ways 0 and 1 -> columns 0, 2)
    bad = eng.state._replace(
        dirm=eng.state.dirm.at[0, 0].set(777).at[0, 2].set(777)
    )
    with pytest.raises(AssertionError, match="duplicate valid LLC tag"):
        check_invariants(cfg, bad)

    # stale barrier_time on an empty slot
    bad = eng.state._replace(
        barrier_time=eng.state.barrier_time.at[0].set(55)
    )
    with pytest.raises(AssertionError, match="barrier_time"):
        check_invariants(cfg, bad)

    # negative LIVE clock (broken rebase); done cores may go negative
    # legitimately, so the check needs the done mask
    bad = eng.state._replace(cycles=eng.state.cycles.at[0].set(-5))
    with pytest.raises(AssertionError, match="clock"):
        check_invariants(cfg, bad, done_mask=np.zeros(4, bool))
    check_invariants(cfg, bad, done_mask=np.ones(4, bool))  # all-done: ok


def test_em_exclusivity_is_structural():
    """E/M exclusivity under pull-based coherence is a THEOREM, not just a
    checked property: effective E/M requires being the directory owner of
    the line's (unique) LLC entry, and an entry has one owner — so even
    deliberately corrupting ownership cannot create two effective E/M
    holders, it only transfers effective ownership (the other core's
    local M validates to I). This is SURVEY.md §5.2's 'data-race-free by
    construction'; the checker's E/M assertion is belt-and-braces against
    future derivation changes. This test pins the self-healing behavior.
    """
    from primesim_tpu.sim.state import init_state
    from primesim_tpu.sim.validate import (
        effective_l1_state,
        l1_views,
        llc_views,
        sharers_view,
    )

    cfg = small_test_config(4)
    st = init_state(cfg)
    line = 7
    b, s2 = line % cfg.n_banks, (line // cfg.n_banks) % cfg.llc.sets
    l1s = line % cfg.l1.sets
    M = 3
    FS = cfg.l1.ways * cfg.l1.sets  # fused-L1 plane stride
    entry_ptr = (b * cfg.llc.sets + s2) * cfg.llc.ways
    mrow = b * cfg.llc.sets + s2  # llc_meta row slot; way-0 tag/owner cols 0/1
    l1 = st.l1
    for c in (0, 1):
        l1 = (
            l1.at[c, l1s].set(line)  # tag plane, way 0
            .at[c, FS + l1s].set(M)  # state plane
            .at[c, 3 * FS + l1s].set(entry_ptr)  # ptr plane
        )
    st = st._replace(
        dirm=st.dirm.at[mrow, 0].set(line).at[mrow, 1].set(0),
        l1=l1,
    )

    def em_holders(state):
        tag_v, own_v, _ = llc_views(cfg, state)
        l1_tag_v, l1_state_v, _, _ = l1_views(cfg, state)
        eff = effective_l1_state(
            cfg, l1_tag_v, l1_state_v,
            tag_v, own_v, sharers_view(cfg, state),
        )
        return sorted(set(np.nonzero((eff >= 2).any(axis=(1, 2)))[0].tolist()))

    check_invariants(cfg, st)
    assert em_holders(st) == [0]  # owner 0 holds M; core 1 validates to I
    flipped = st._replace(dirm=st.dirm.at[mrow, 1].set(1))
    check_invariants(cfg, flipped)  # still consistent: ownership moved
    assert em_holders(flipped) == [1]
