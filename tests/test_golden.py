"""Hand-computed microbenchmark tests for the golden simulator.

These pin down DESIGN.md's latency composition on tiny traces where the
expected cycle counts can be derived by hand. The JAX engine is then required
to match the golden model bit-exactly (test_parity.py), so these tests anchor
the whole fidelity story.
"""


from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace.format import EV_INS, EV_LD, EV_ST, from_event_lists


def cfg1(**kw):
    """1 core, 1 bank, 1x1 mesh: all NoC latencies = router_lat (0 hops)."""
    defaults = dict(
        n_cores=1,
        l1=CacheConfig(size=256, ways=2, line=64, latency=2),  # 2 sets
        llc=CacheConfig(size=1024, ways=4, line=64, latency=10),  # 4 sets
        n_banks=1,
        noc=NocConfig(mesh_x=1, mesh_y=1, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=10_000,
    )
    defaults.update(kw)
    return MachineConfig(**defaults)


def run(cfg, per_core):
    sim = GoldenSim(cfg, from_event_lists(per_core))
    sim.run()
    return sim


def test_ins_only():
    sim = run(cfg1(), [[(EV_INS, 100, 0)]])
    assert sim.cycles[0] == 100
    assert sim.counters["instructions"][0] == 100


def test_ins_cpi2():
    import dataclasses

    cfg = cfg1()
    cfg = dataclasses.replace(cfg, core=dataclasses.replace(cfg.core, cpi=2))
    sim = run(cfg, [[(EV_INS, 50, 0)]])
    assert sim.cycles[0] == 100


def test_cold_miss_then_hit():
    cfg = cfg1()
    # cold read miss: l1_lat(2) + req one_way(0 hops -> router 1) + llc(10)
    #               + dram(100) + reply(1) = 114; then read hit: +2
    sim = run(cfg, [[(EV_LD, 4, 0x1000), (EV_LD, 4, 0x1000)]])
    assert sim.cycles[0] == 114 + 2
    assert sim.counters["l1_read_misses"][0] == 1
    assert sim.counters["l1_read_hits"][0] == 1
    assert sim.counters["llc_misses"][0] == 1
    assert sim.counters["dram_accesses"][0] == 1
    assert sim.counters["noc_msgs"][0] == 2 + 2  # req+reply + 2 dram msgs
    assert sim.counters["instructions"][0] == 2


def test_llc_hit_after_l1_eviction():
    cfg = cfg1()
    line = 64
    # 2 L1 sets -> lines 0,2,4 all map to set 0 (line_addr % 2 == 0); 2 ways
    # -> third distinct line evicts LRU. LLC has 4 sets: lines 0,2,4 distinct
    # LLC sets (line % 1 bank, (line//1)%4) -> no LLC conflict.
    a0, a2, a4 = 0 * line, 2 * line, 4 * line
    evs = [
        (EV_LD, 4, a0),  # cold: 114
        (EV_LD, 4, a2),  # cold: 114
        (EV_LD, 4, a4),  # cold: 114, evicts a0 (LRU)
        (EV_LD, 4, a0),  # LLC hit: l1(2)+req(1)+llc(10)+reply(1) = 14
    ]
    sim = run(cfg, [evs])
    assert sim.cycles[0] == 114 * 3 + 14
    assert sim.counters["llc_hits"][0] == 1
    assert sim.counters["llc_misses"][0] == 3


def test_write_hit_e_to_m_silent():
    cfg = cfg1()
    sim = run(cfg, [[(EV_LD, 4, 0), (EV_ST, 4, 0)]])
    # read cold miss grants E (no other sharers) = 114; write hit on E = +2
    assert sim.cycles[0] == 116
    assert sim.counters["l1_write_hits"][0] == 1
    assert sim.counters["upgrades"][0] == 0
    assert sim.l1_state[0, 0, 0] == 3  # M


def test_write_miss_grants_m():
    cfg = cfg1()
    sim = run(cfg, [[(EV_ST, 4, 0), (EV_ST, 4, 0)]])
    assert sim.cycles[0] == 114 + 2
    assert sim.counters["l1_write_misses"][0] == 1
    assert sim.counters["l1_write_hits"][0] == 1


def two_core_cfg(**kw):
    defaults = dict(
        n_cores=2,
        l1=CacheConfig(size=256, ways=2, line=64, latency=2),
        llc=CacheConfig(size=1024, ways=4, line=64, latency=10),
        n_banks=1,
        noc=NocConfig(mesh_x=2, mesh_y=1, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=100_000,
    )
    defaults.update(kw)
    return MachineConfig(**defaults)


def test_read_sharing_two_cores():
    """Core 0 reads line (gets E); core 1 reads same line (probe, both S)."""
    cfg = two_core_cfg()
    # Tiles: core0 -> tile0, core1 -> tile1, bank0 -> tile0.
    # Core 0 first (INS delay on core 1 orders the requests):
    #  c0 cold: l1(2) + ow(t0,t0)=1 + llc(10) + dram(100) + ow=1 = 114 -> E
    #  c1 read: l1(2) + ow(t1,t0)=hops1*link1+2*router=3 + llc(10)
    #           + probe: ow(t0,t0)*2 = 2 + reply ow(t0,t1)=3 => 2+3+10+2+3=20
    sim = run(
        cfg,
        [
            [(EV_LD, 4, 0)],
            [(EV_INS, 200, 0), (EV_LD, 4, 0)],
        ],
    )
    assert sim.cycles[0] == 114
    assert sim.cycles[1] == 200 + 20
    assert sim.counters["probes"][1] == 1
    # both cores end in S
    assert sim.l1_state[0, 0, 0] == 1
    assert sim.l1_state[1, 0, 0] == 1
    assert sim.llc_owner[0, 0, 0] == -1


def test_write_invalidates_sharers():
    """Both cores share a line; core 1 writes -> upgrade invalidates core 0."""
    cfg = two_core_cfg()
    sim = run(
        cfg,
        [
            [(EV_LD, 4, 0)],
            [(EV_INS, 200, 0), (EV_LD, 4, 0), (EV_ST, 4, 0)],
        ],
    )
    # After both reads: sharers {0,1}. Core 1 ST in S -> UPG:
    #   l1(2) + req ow(t1,t0)=3 + llc(10) + inv max rt: target core0 tile0,
    #   rt = 2*ow(t0,t0) = 2 -> +2, + reply 3 => 20
    assert sim.cycles[1] == 200 + 20 + 20
    assert sim.counters["upgrades"][1] == 1
    assert sim.counters["invalidations"][1] == 1
    assert sim.l1_state[0, 0, 0] == 0  # I (invalidated)
    assert sim.l1_state[1, 0, 0] == 3  # M
    assert sim.llc_owner[0, 0, 0] == 1


def test_quantum_barrier_bounds_skew():
    """A fast core stalls at the quantum boundary until the slow core catches up."""
    cfg = two_core_cfg(quantum=100)
    # core 0: 1000 instructions in batches of 10 -> 100 events, 1000 cycles
    # core 1: same work. Both must finish; cycles equal.
    evs0 = [(EV_INS, 10, 0)] * 100
    evs1 = [(EV_INS, 10, 0)] * 100
    sim = run(cfg, [evs0, evs1])
    assert sim.cycles[0] == 1000
    assert sim.cycles[1] == 1000
    # quantum_end advanced in steps of 100
    assert sim.quantum_end % 100 == 0


def test_false_sharing_ping_pong():
    """Alternating writers to one line: every write after the first probes."""
    cfg = two_core_cfg()
    sim = run(
        cfg,
        [
            [(EV_ST, 4, 0), (EV_INS, 500, 0), (EV_ST, 4, 0)],
            [(EV_INS, 250, 0), (EV_ST, 4, 0)],
        ],
    )
    # c0 write cold at t=0: 114 -> M, owner=0
    # c1 write at t=250: GETM hit, probe-inv owner(c0):
    #   l1 2 + req 3 + llc 10 + probe 2*ow(t0,t0)=2 + reply 3 = 20 -> M owner=1
    # c0 write at t=614: GETM hit, probe-inv owner(c1):
    #   l1 2 + req ow(t0,t0)=1 + llc 10 + probe 2*ow(t0,t1)=6 + reply 1 = 20
    assert sim.cycles[1] == 250 + 20
    assert sim.cycles[0] == 114 + 500 + 20
    assert sim.counters["probes"][0] == 1
    assert sim.counters["probes"][1] == 1


def test_llc_back_invalidation():
    """LLC victim eviction invalidates the L1 copy (inclusive LLC)."""
    line = 64
    cfg = cfg1(
        llc=CacheConfig(size=128, ways=2, line=64, latency=10),  # 1 set, 2 ways
        l1=CacheConfig(size=512, ways=4, line=64, latency=2),  # 2 sets, 4 ways
    )
    a = [i * line for i in range(3)]
    sim = run(cfg, [[(EV_LD, 4, a[0]), (EV_LD, 4, a[1]), (EV_LD, 4, a[2]), (EV_LD, 4, a[0])]])
    # third load evicts line0 from LLC (LRU) and back-invalidates core 0's
    # L1 copy -> fourth load misses all the way to DRAM again.
    assert sim.counters["llc_misses"][0] == 4
    assert sim.counters["invalidations"][0] >= 1


def test_sharer_bitvector_many_cores():
    """33 sharers crosses the 32-bit word boundary in the sharer vector."""
    n = 64
    cfg = MachineConfig(
        n_cores=n,
        l1=CacheConfig(size=256, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=10),
        n_banks=4,
        noc=NocConfig(mesh_x=4, mesh_y=4, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=1_000_000,
    )
    per_core = [[(EV_INS, 10 * (c + 1), 0), (EV_LD, 4, 0)] for c in range(40)]
    per_core += [[] for _ in range(n - 40)]
    # writer comes last
    per_core[63] = [(EV_INS, 100_000, 0), (EV_ST, 4, 0)]
    sim = GoldenSim(cfg, from_event_lists(per_core))
    sim.run()
    assert sim.counters["invalidations"][63] == 40  # all 40 sharers invalidated
    for c in range(40):
        assert sim.l1_state[c, 0, 0] == 0  # I
    assert sim.l1_state[63, 0, 0] == 3  # M
