"""CLI + shipped configs + report writer (SURVEY.md §2 #12/#14/#15)."""

import glob
import json
import os

import pytest

from primesim_tpu.cli import main
from primesim_tpu.config.machine import MachineConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(REPO, "configs", "*.json")))


def test_ladder_configs_ship_and_validate():
    ladder = [p for p in CONFIGS if os.path.basename(p).startswith("rung")]
    assert len(ladder) == 5, ladder  # the five BASELINE rungs
    names = [os.path.basename(p) for p in ladder]
    for n, cores in zip(
        sorted(names), [64, 256, 1024, 4096, 16384]
    ):
        assert str(cores) in n, (n, cores)
    for p in ladder:
        with open(p) as f:
            cfg = MachineConfig.from_json(f.read())  # __post_init__ validates
        assert cfg.n_cores in (64, 256, 1024, 4096, 16384)
        # round trip through to_json preserves the machine
        assert MachineConfig.from_json(cfg.to_json()) == cfg


def test_zoo_and_calib_configs_ship_and_validate():
    zoo = [p for p in CONFIGS if os.path.basename(p).startswith("zoo_")]
    assert len(zoo) == 2, zoo
    for p in zoo:
        with open(p) as f:
            cfg = MachineConfig.from_json(f.read())
        assert cfg.noc.topology in ("mesh", "torus", "ring")
        assert MachineConfig.from_json(cfg.to_json()) == cfg
    from primesim_tpu.calib.table import parse_table

    with open(os.path.join(REPO, "configs", "calib_ipu_microbench.json")) as f:
        table = parse_table(f.read())
    assert table.entries and all(e.metric for e in table.entries)


def test_biglittle_pattern_tiles():
    with open(os.path.join(REPO, "configs", "rung4_4096core_biglittle.json")) as f:
        cfg = MachineConfig.from_json(f.read())
    v = cfg.core.cpi_vector(cfg.n_cores)
    assert len(v) == 4096 and v[0] == 1 and v[4] == 3 and v[8] == 1


def test_cli_run_golden_and_report(tmp_path, capsys):
    cfg = os.path.join(REPO, "configs", "rung1_64core_fft.json")
    rpt = str(tmp_path / "report.txt")
    rc = main(
        [
            "run", cfg,
            "--synth", "fft_like:n_phases=2,points_per_core=8",
            "--engine", "golden",
            "--report", rpt,
            "--per-core-limit", "2",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["unit"] == "MIPS" and summary["detail"]["n_cores"] == 64
    text = open(rpt).read()
    assert "AGGREGATE" in text and "PER-CORE" in text
    assert f"{summary['detail']['instructions']:,}" in text


def test_cli_synth_roundtrip_run_jax(tmp_path, capsys):
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    tr_path = str(tmp_path / "t.ptpu")
    rc = main(
        ["synth", "lock_contention:n_critical=4", "--cores", "8",
         "--out", tr_path, "--fold"]
    )
    assert rc == 0 and os.path.exists(tr_path)
    rc = main(["run", cfg_path, "--trace", tr_path, "--engine", "jax",
               "--chunk-steps", "32"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["detail"]["engine"] == "jax"
    assert summary["detail"]["instructions"] > 0


def test_cli_engines_agree(tmp_path, capsys):
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    results = {}
    for eng in ("golden", "jax"):
        rc = main(
            ["run", cfg_path, "--synth", "false_sharing:n_mem_ops=40",
             "--engine", eng]
        )
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        d = json.loads(out)["detail"]
        results[eng] = (d["instructions"], d["max_core_cycles"], d["noc_msgs"])
    assert results["golden"] == results["jax"]


def test_cli_rejects_bad_input(tmp_path):
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    with pytest.raises(SystemExit):
        main(["run", cfg_path])  # no trace source
    with pytest.raises(SystemExit):
        main(["run", cfg_path, "--synth", "nonsense_gen"])
    with pytest.raises(SystemExit):
        main(["run", cfg_path, "--synth", "fft_like:bogus"])  # bad k=v


def test_cli_xprof_writes_trace(tmp_path, capsys):
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=4, n_banks=4).to_json())
    prof = str(tmp_path / "prof")
    rc = main(
        ["run", cfg_path, "--synth", "stream:n_mem_ops=10",
         "--chunk-steps", "16", "--xprof", prof]
    )
    assert rc == 0
    capsys.readouterr()
    found = [p for p in glob.glob(prof + "/**/*", recursive=True)
             if os.path.isfile(p)]
    assert found, "profiler trace directory is empty"


def test_cli_info(capsys):
    cfg = os.path.join(REPO, "configs", "rung3_1024core_o3.json")
    assert main(["info", cfg]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["n_cores"] == 1024 and d["core"]["o3_overlap_256"] == 128


def test_cli_devices_runs_sharded(tmp_path, capsys):
    # --devices N shards the machine over N (virtual CPU) devices and
    # still produces the exact single-device result (VERDICT r4 #8)
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=16, n_banks=8).to_json())
    args = ["run", cfg_path, "--synth", "false_sharing:n_mem_ops=20",
            "--chunk-steps", "16"]
    assert main(args) == 0
    single = json.loads(capsys.readouterr().out)
    assert main(args + ["--devices", "8"]) == 0
    sharded = json.loads(capsys.readouterr().out)
    assert sharded["detail"]["instructions"] == single["detail"]["instructions"]
    assert (
        sharded["detail"]["max_core_cycles"]
        == single["detail"]["max_core_cycles"]
    )
    # golden engine has no device loop to shard
    with pytest.raises(SystemExit):
        main(args + ["--devices", "8", "--engine", "golden"])


def test_cli_capture_online(tmp_path, capsys):
    # one-command execution-driven mode: build the example binary, run it
    # under `primetpu capture`, simulating WHILE it executes
    import shutil
    import subprocess

    if shutil.which("gcc") is None:
        import pytest

        pytest.skip("no native toolchain")
    frontend = os.path.join(REPO, "primesim_tpu", "frontend")
    binary = str(tmp_path / "ocean_like")
    subprocess.run(
        ["gcc", "-O2", "-fno-builtin", "-o", binary,
         os.path.join(frontend, "examples", "ocean_like.c"), "-lpthread"],
        check=True, capture_output=True,
    )
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(
            MachineConfig(
                n_cores=3, n_banks=4, quantum=10_000
            ).to_json()
        )
    rc = main(["capture", cfg_path, "--window", "256", "--",
               binary, "2", "1", "2"])
    assert rc == 0
    d = json.loads(capsys.readouterr().out)
    assert d["detail"]["engine"] == "online"
    assert d["detail"]["instructions"] > 0
    assert d["detail"]["events"] > 0
