"""Synchronization-event semantics (DESIGN.md phase 2.7).

The reference models pthread mutex/barrier calls by Pin interception
(SURVEY.md §2 #1, §3.5); here the PTPU v3 LOCK/UNLOCK/BARRIER events drive
lock-table arbitration and barrier freeze/release in both engines. Tests:

- hand-computed golden cycle counts for the canonical cases (uncontended
  lock, contended lock with unlock-then-grant in the same step, spin
  charging, barrier release, barrier slot reuse, lock-slot collision);
- golden-vs-JAX bit-exact parity on every hand trace and on the sync
  workload generators (incl. folded `pre` batches and local runs);
- the relaxed-sync fidelity bound: lock grant order is step order, so
  mutual exclusion in SIMULATED time may be violated by at most one
  quantum (DESIGN.md §3-sync caveat) — asserted by tracking every
  holder transition;
- clock rebase across chunk boundaries with an OCCUPIED barrier slot
  (barrier_time is epoch-relative and must rebase with the clocks).
"""

import dataclasses

import numpy as np
import pytest

from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import (
    EV_BARRIER,
    EV_INS,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
    fold_ins,
    from_event_lists,
)

from test_parity import assert_parity

# small_test_config(4): 2x2 mesh (one_way lat = 2*hops + 1), l1 lat 2,
# llc lat 10, dram 100, quantum 1000, cpi 1. core_tile(c) = c % 4.
# Mutex addr 0 -> line 0 -> slot 0 -> home bank 0 -> tile 0.
# Lock round trip from core 0: 1 + 10 + 1 = 12; from core 1: 3 + 10 + 3 = 16.


def cfg4(**kw) -> MachineConfig:
    return small_test_config(4, **kw)


def run_golden(cfg, trace):
    g = GoldenSim(cfg, trace)
    g.run()
    return g


def test_golden_uncontended_lock():
    tr = from_event_lists(
        [[(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)], [], [], []]
    )
    g = run_golden(cfg4(), tr)
    assert g.cycles[0] == 12 + 12  # acquire RT + release RT
    assert g.counters["lock_acquires"][0] == 1
    assert g.counters["lock_spins"][0] == 0
    assert g.counters["instructions"][0] == 2
    assert g.counters["noc_msgs"][0] == 4
    assert g.lock_holder[0] == -1  # released at the end
    assert_parity(cfg4(), tr)


def test_golden_contended_lock_unlock_then_grant_same_step():
    # Both cores request at cycle 0; core 0 wins by (cycles, core_id).
    # Step 2: core 0's UNLOCK and core 1's retry happen in the SAME step —
    # unlocks are processed before grants, so core 1 acquires immediately.
    tr = from_event_lists(
        [
            [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
            [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
            [],
            [],
        ]
    )
    g = run_golden(cfg4(), tr)
    np.testing.assert_array_equal(g.cycles[:2], [24, 48])
    np.testing.assert_array_equal(g.counters["lock_acquires"][:2], [1, 1])
    np.testing.assert_array_equal(g.counters["lock_spins"][:2], [0, 1])
    assert_parity(cfg4(), tr)


def test_golden_spin_charging():
    # Core 0 holds the lock across an INS batch; core 1 spins, paying a
    # full RMW round trip (16 cycles from tile 1) per failed attempt.
    tr = from_event_lists(
        [
            [(EV_LOCK, 0, 0), (EV_INS, 100, 0), (EV_UNLOCK, 0, 0)],
            [(EV_LOCK, 0, 0)],
            [],
            [],
        ]
    )
    g = run_golden(cfg4(), tr)
    # c0: 12 (grant) + 100 (INS) + 12 (unlock) = 124
    # c1: spin@step1 16, spin@step2 32, grant@step3 48
    np.testing.assert_array_equal(g.cycles[:2], [124, 48])
    assert g.counters["lock_spins"][1] == 2
    assert g.counters["lock_acquires"][1] == 1
    assert g.lock_holder[0] == 1  # never unlocked by core 1
    assert_parity(cfg4(), tr)


def test_golden_barrier_release():
    # c0 arrives at cycle 1 (tile 0 -> home 0: lat 1); c1 works 50 cycles
    # then arrives at 53 (tile 1 -> home 0: lat 3). Both release from the
    # slot max (53) + wake-up message.
    tr = from_event_lists(
        [
            [(EV_BARRIER, 2, 0)],
            [(EV_INS, 50, 0), (EV_BARRIER, 2, 0)],
            [],
            [],
        ]
    )
    g = run_golden(cfg4(), tr)
    np.testing.assert_array_equal(g.cycles[:2], [54, 56])
    np.testing.assert_array_equal(g.counters["barrier_waits"][:2], [1, 1])
    np.testing.assert_array_equal(g.counters["instructions"][:2], [1, 51])
    assert g.barrier_count[0] == 0 and g.barrier_time[0] == 0  # drained
    assert_parity(cfg4(), tr)


def test_golden_barrier_reuse():
    # The same barrier id is used twice: the slot must re-arm (count and
    # max-arrival clock reset) after the first release.
    tr = from_event_lists(
        [
            [(EV_BARRIER, 2, 0), (EV_BARRIER, 2, 0)],
            [(EV_BARRIER, 2, 0), (EV_INS, 10, 0), (EV_BARRIER, 2, 0)],
            [],
            [],
        ]
    )
    g = run_golden(cfg4(), tr)
    # round 1: arrivals 1 and 3 -> release at 3: c0=4, c1=6
    # round 2: c0 arrives 5; c1 works to 16, arrives 19 -> c0=20, c1=22
    np.testing.assert_array_equal(g.cycles[:2], [20, 22])
    np.testing.assert_array_equal(g.counters["barrier_waits"][:2], [2, 2])
    assert_parity(cfg4(), tr)


def test_golden_lock_slot_collision():
    # Two DISTINCT mutexes whose lines collide in the lock table (line 0
    # and line 1024 with lock_slots=1024) contend conservatively; with a
    # 2048-slot table they do not.
    m2 = 1024 * 64
    evs = [
        [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
        [(EV_LOCK, 0, m2), (EV_UNLOCK, 0, m2)],
        [],
        [],
    ]
    g = run_golden(cfg4(lock_slots=1024), from_event_lists(evs))
    assert g.counters["lock_spins"][1] == 1  # false contention
    g2 = run_golden(cfg4(lock_slots=2048), from_event_lists(evs))
    assert g2.counters["lock_spins"][1] == 0  # distinct slots
    assert_parity(cfg4(lock_slots=1024), from_event_lists(evs))
    assert_parity(cfg4(lock_slots=2048), from_event_lists(evs))


def test_golden_lock_reacquire():
    # A core that already holds the lock re-acquires it immediately even
    # if another, earlier-keyed core is spinning on the slot.
    tr = from_event_lists(
        [
            [
                (EV_LOCK, 0, 0),
                (EV_INS, 5, 0),
                (EV_LOCK, 0, 0),  # re-acquire while c1 spins
                (EV_UNLOCK, 0, 0),
            ],
            [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
            [],
            [],
        ]
    )
    g = run_golden(cfg4(), tr)
    assert g.counters["lock_acquires"][0] == 2
    assert g.counters["lock_acquires"][1] == 1
    assert g.counters["lock_spins"][1] >= 2  # spun while c0 held + reheld
    assert_parity(cfg4(), tr)


def test_relaxed_sync_skew_bounded_by_quantum():
    """Lock grant order is STEP order, not simulated-time order: a waiter
    may acquire at a simulated cycle earlier than the holder's release
    cycle. DESIGN.md's clock-window invariant bounds this skew by one
    quantum — track every holder transition and assert
    acquire_cycle >= release_cycle - Q."""
    Q = 64
    cfg = small_test_config(8, quantum=Q)
    tr = synth.lock_contention(8, n_critical=6, n_locks=2, seed=7)
    g = GoldenSim(cfg, tr)
    last_release = {}  # slot -> release cycle of previous holder
    prev = g.lock_holder.copy()
    violations = []
    for _ in range(10_000):
        if g.done():
            break
        g.step()
        for s in np.nonzero(g.lock_holder != prev)[0]:
            old, new = int(prev[s]), int(g.lock_holder[s])
            if old >= 0 and new != old:
                last_release[s] = int(g.cycles[old])
            if new >= 0 and new != old:
                acq = int(g.cycles[new])
                if s in last_release and acq < last_release[s] - Q:
                    violations.append((s, acq, last_release[s]))
        prev = g.lock_holder.copy()
    assert g.done()
    assert not violations, violations


# ---------------------------------------------------------- parity (gens)


@pytest.mark.parametrize("subset", [False, True])
def test_parity_barrier_phases(subset):
    cfg = small_test_config(8, n_banks=4)
    assert_parity(cfg, synth.barrier_phases(8, n_phases=3, subset=subset, seed=21))


def test_parity_lock_contention_folded_local_runs():
    # folded pre batches + local runs + sync in one config: sync events
    # must stop local runs and charge their pre batch exactly once
    cfg = small_test_config(8, n_banks=4, local_run_len=4)
    assert_parity(cfg, fold_ins(synth.lock_contention(8, n_critical=10, seed=22)))


def test_parity_sync_small_quantum():
    cfg = small_test_config(8, n_banks=4, quantum=64)
    assert_parity(cfg, synth.lock_contention(8, n_critical=8, seed=23), chunk_steps=50)
    assert_parity(cfg, synth.barrier_phases(8, n_phases=2, seed=24), chunk_steps=50)


def test_parity_barrier_across_rebase():
    """A frozen barrier waiter holds an epoch-relative arrival clock in
    barrier_time; chunk-boundary clock rebases (both the on-device run_loop
    and the host run_chunked variant) must rebase occupied barrier slots
    with the core clocks or the release cycle is wrong by delta.

    Core 0 works ~10k cycles then waits; core 1 grinds through 400 small
    INS events (the rebase delta tracks core 1's clock while core 0 is
    frozen). quantum=64 and chunk_steps=16 force many rebases while the
    slot is occupied.
    """
    from primesim_tpu.sim.engine import Engine

    cfg = small_test_config(2, n_banks=2, quantum=64)
    tr = from_event_lists(
        [
            [(EV_INS, 10_000, 0), (EV_BARRIER, 2, 0), (EV_LD, 4, 0)],
            [(EV_INS, 50, 0)] * 400 + [(EV_BARRIER, 2, 0), (EV_LD, 4, 64)],
        ]
    )
    g = run_golden(cfg, tr)
    e = Engine(cfg, tr, chunk_steps=16)
    e.run()
    np.testing.assert_array_equal(e.cycles, g.cycles)
    e2 = Engine(cfg, tr, chunk_steps=16)
    e2.run_chunked()
    np.testing.assert_array_equal(e2.cycles, g.cycles)


def test_parity_mixed_barrier_then_locks():
    """Stress the clock-window invariant (DESIGN.md §3-sync): a subset
    barrier's waiters freeze with early clocks while non-participants
    free-run thousands of cycles; afterwards ALL cores contend the same
    lock. The packed arbitration key is only exact if released waiters
    re-enter the Q-window — golden asserts the invariant every step and
    parity proves the key stayed exact."""
    from primesim_tpu.trace.format import EV_INS

    cfg = small_test_config(4, quantum=64)
    tr = from_event_lists(
        [
            [(EV_BARRIER, 2, 0), (EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
            [
                (EV_INS, 20_000, 0),
                (EV_BARRIER, 2, 0),
                (EV_LOCK, 0, 0),
                (EV_UNLOCK, 0, 0),
            ],
            [(EV_INS, 50, 0)] * 600 + [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
            [(EV_INS, 50, 0)] * 600 + [(EV_LOCK, 0, 0), (EV_UNLOCK, 0, 0)],
        ]
    )
    assert_parity(cfg, tr, chunk_steps=50)


def test_trace_rejects_bad_barrier_ids():
    from primesim_tpu.sim.engine import Engine

    cfg = small_test_config(2, n_banks=2, barrier_slots=4)
    tr = from_event_lists([[(EV_BARRIER, 2, 9)], [(EV_BARRIER, 2, 9)]])
    with pytest.raises(ValueError, match="barrier ids"):
        GoldenSim(cfg, tr)
    with pytest.raises(ValueError, match="barrier ids"):
        Engine(cfg, tr)
