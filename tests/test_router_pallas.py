"""Pallas router-cascade kernel (kernels/router_kernels.py, ISSUE 6):
with `step_impl="pallas"` on a router-NoC config, the wait-floor +
cummax-cascade + departure block runs as one VMEM kernel — and must be
BIT-EXACT against both the golden scalar walk and the XLA step, on
every workload generator, with the DRAM queue, under fault-injection
detours, and fleet-vmapped.  Interpreter mode on CPU runs the identical
kernel logic tier-1-gated; compiled on TPU."""

import dataclasses

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    FAULT_CORE_FAILSTOP,
    FAULT_LINK_DEGRADE,
    FAULT_LINK_FAIL,
    NocConfig,
    small_test_config,
)
from primesim_tpu.trace import synth

from test_parity import assert_parity
from test_step_pallas import GENERATOR_TRACES, assert_xla_pallas_match


def _router_cfg(**kw):
    noc = NocConfig(
        mesh_x=2, mesh_y=2, link_lat=1, router_lat=1,
        contention=True, contention_model="router", contention_lat=2,
    )
    return small_test_config(8, n_banks=4, quantum=400, noc=noc, **kw)


def _pallas(cfg):
    return dataclasses.replace(cfg, step_impl="pallas")


@pytest.mark.parametrize("gen", sorted(GENERATOR_TRACES))
def test_three_way_router_parity_every_generator(gen):
    # golden vs pallas (assert_parity) AND xla vs pallas full state on a
    # ROUTER-contention machine: the cascade kernel sits in the hot path
    # of every trace shape, sync and async alike
    cfg = _router_cfg()
    tr = GENERATOR_TRACES[gen]()
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


def test_router_plus_dram_queue_parity():
    # both FIFO blocks live (shared lane_order feeds both segmented
    # ranks); queue clocks carry across steps through the kernel path
    cfg = _router_cfg(dram_queue=True, dram_service=8)
    tr = synth.uniform_random(8, n_mem_ops=60, seed=31)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


@pytest.mark.slow
def test_router_local_runs_and_larger_mesh():
    # rl > 0 composes (deferred run patches change t0 inputs), and a
    # 4x4 mesh exercises H = 6 hop columns with multi-block cores
    noc = NocConfig(
        mesh_x=4, mesh_y=4, link_lat=2, router_lat=1,
        contention=True, contention_model="router", contention_lat=3,
    )
    cfg = small_test_config(
        16, n_banks=16, quantum=500, noc=noc, local_run_len=4
    )
    tr = synth.fft_like(16, n_phases=2, points_per_core=8, seed=32)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


@pytest.mark.slow
@pytest.mark.parametrize(
    "events",
    [
        ((0, FAULT_LINK_FAIL, 1, 0),),
        ((0, FAULT_LINK_DEGRADE, 2, 5), (4, FAULT_CORE_FAILSTOP, 3, 0)),
    ],
    ids=["link-fail-detour", "degrade+failstop"],
)
def test_router_fault_detours_compose_with_kernel(events):
    # fault detour extras join AFTER the router walk (nominal paths):
    # the kernel path must compose with them unchanged, xla == pallas
    # on cycles, counters (noc_reroutes included), and full state
    cfg = _router_cfg(
        faults_enabled=True, max_fault_events=2,
        fault_events=events, fault_seed=7,
    )
    tr = synth.uniform_random(8, n_mem_ops=50, seed=33)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


@pytest.mark.slow
def test_fleet_vmapped_router_kernel_bit_exact_vs_solo():
    # the fleet vmaps the whole step including the cascade kernel: per
    # element results must equal solo runs bit-for-bit, with traced knob
    # overrides compiling ONCE
    from primesim_tpu.sim.fleet import FleetEngine, apply_overrides

    from test_fleet import assert_element_matches_solo

    cfg = _pallas(_router_cfg(dram_queue=True, dram_service=6))
    traces = [
        synth.uniform_random(8, n_mem_ops=40, seed=41),
        synth.barrier_phases(8, n_phases=3, seed=42),
        synth.false_sharing(8, n_mem_ops=40, seed=43),
    ]
    overrides = [{}, {"link_lat": 3, "router_lat": 2}, {"quantum": 150}]
    fleet = FleetEngine(cfg, traces, overrides, chunk_steps=32)
    fleet.run()
    assert fleet.done()
    for i, (t, ov) in enumerate(zip(traces, overrides)):
        assert_element_matches_solo(
            fleet, i, apply_overrides(cfg, ov), t, chunk_steps=32
        )


@pytest.mark.slow
def test_fleet_faulted_router_replay_solo_vs_vmapped():
    # chaos acceptance: faults-on router runs replay bit-exactly solo vs
    # fleet-vmapped through the kernel (counters included)
    from primesim_tpu.sim.fleet import FleetEngine

    from test_fleet import assert_element_matches_solo

    cfg = _pallas(_router_cfg(
        faults_enabled=True, max_fault_events=1,
        fault_events=((2, FAULT_LINK_FAIL, 1, 0),), fault_seed=11,
    ))
    traces = [
        synth.uniform_random(8, n_mem_ops=40, seed=44),
        synth.stream(8, n_mem_ops=40, seed=45),
    ]
    fleet = FleetEngine(cfg, traces, chunk_steps=32)
    fleet.run()
    assert fleet.done()
    for i, t in enumerate(traces):
        assert_element_matches_solo(fleet, i, cfg, t, chunk_steps=32)


def test_cascade_kernel_matches_xla_reference_directly():
    # unit-level: random wait-floor inputs through router_cascade vs a
    # straight jnp transcription of the engine's _cascade
    import jax.numpy as jnp

    from primesim_tpu.kernels.router_kernels import SENT, router_cascade

    rng = np.random.default_rng(3)
    C, H, legs = 16, 6, 3
    LT = legs * H
    lf = rng.integers(0, 900, (C, LT)).astype(np.int32)
    bs = rng.integers(0, 900, (C, LT)).astype(np.int32)
    r = rng.integers(0, 8, (C, LT)).astype(np.int32)
    ok = rng.random((C, LT)) < 0.5
    t0 = rng.integers(0, 500, C).astype(np.int32)
    service = rng.integers(1, 60, C).astype(np.int32)
    hops = [rng.integers(0, H + 1, C).astype(np.int32) for _ in range(3)]
    L_lat, R_lat = 2, 3
    c_hop = L_lat + R_lat
    hidx = np.arange(H, dtype=np.int32)[None, :]

    F = np.where(ok, np.maximum(lf, bs) + r * L_lat, SENT)

    def cascade(t_start, Fl, nh):
        G = Fl - hidx * c_hop
        cum = np.maximum.accumulate(G, axis=1)
        t1 = t_start + R_lat
        t_end = np.maximum(t1, cum[:, -1]) + nh * c_hop
        departs = np.maximum(t1[:, None], cum) + hidx * c_hop + L_lat
        return t_end, departs

    te_req, d_req = cascade(t0, F[:, :H], hops[0])
    te_rep, d_rep = cascade(te_req + service, F[:, H : 2 * H], hops[1])
    te_arr, d_arr = cascade(t0, F[:, 2 * H :], hops[2])

    t_rep_end, t_arr_end, d_all = router_cascade(
        jnp.asarray(lf), jnp.asarray(bs), jnp.asarray(r),
        jnp.asarray(ok), jnp.asarray(t0), jnp.asarray(service),
        jnp.asarray(hops[0]), jnp.asarray(hops[1]), jnp.asarray(hops[2]),
        L_lat, R_lat, has_sync=True,
    )
    np.testing.assert_array_equal(np.asarray(t_rep_end), te_rep)
    np.testing.assert_array_equal(np.asarray(t_arr_end), te_arr)
    np.testing.assert_array_equal(
        np.asarray(d_all), np.concatenate([d_req, d_rep, d_arr], axis=1)
    )
