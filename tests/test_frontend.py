"""Execution-capture frontend (SURVEY.md §2 #1): build the LD_PRELOAD
shim, capture a REAL multithreaded pthread binary (ocean_like: grid
relaxation phases + mutex-protected reduction + global barriers), and
prove the captured trace simulates with golden/engine bit-exact parity —
the reference's defining capability (simulating real programs), VERDICT
round-3 item #3.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.ingest.capture import build_shim, capture_run
from primesim_tpu.trace.format import (
    EV_BARRIER,
    EV_LD,
    EV_LOCK,
    EV_ST,
    EV_UNLOCK,
)

from test_parity import assert_parity

FRONTEND = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "primesim_tpu",
    "frontend",
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("gcc") is None,
    reason="native toolchain unavailable",
)


@pytest.fixture(scope="module")
def ocean_binary(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("frontend") / "ocean_like")
    # -fno-builtin keeps memcpy/memset as libc PLT calls the shim can
    # interpose (fully optimized builds may inline them; sync capture is
    # unaffected either way)
    subprocess.run(
        [
            "gcc", "-O2", "-fno-builtin", "-o", out,
            os.path.join(FRONTEND, "examples", "ocean_like.c"), "-lpthread",
        ],
        check=True,
        capture_output=True,
    )
    return out


N_THREADS, N_PHASES, ROWS = 4, 3, 4
LINES_PER_ROW = 256 * 8 // 64  # COLS doubles per 64B line


@pytest.fixture(scope="module")
def captured(ocean_binary):
    build_shim()
    return capture_run(
        [ocean_binary, str(N_THREADS), str(N_PHASES), str(ROWS)], line=64
    )


def test_capture_structure(captured):
    t = captured
    assert t.n_cores == N_THREADS + 1  # workers + main thread (core 0)
    types = t.events[:, :, 0]
    for c in range(1, t.n_cores):  # each worker thread
        row = types[c, : t.lengths[c]]
        assert (row == EV_LOCK).sum() == N_PHASES
        assert (row == EV_UNLOCK).sum() == N_PHASES
        assert (row == EV_BARRIER).sum() == N_PHASES
        # phase row copy-backs: >= rows*phases*lines LD and ST from memcpy
        assert (row == EV_LD).sum() >= N_PHASES * ROWS * LINES_PER_ROW
        assert (row == EV_ST).sum() >= N_PHASES * ROWS * LINES_PER_ROW
    # barrier events carry the registered participant count and dense id 0
    bar = t.events[:, :, 0] == EV_BARRIER
    assert (t.events[:, :, 1][bar] == N_THREADS).all()
    assert (t.events[:, :, 2][bar] == 0).all()
    # all worker threads hammer the same mutex address
    lock_addrs = t.events[:, :, 2][t.events[:, :, 0] == EV_LOCK]
    assert len(np.unique(lock_addrs)) == 1


def test_captured_trace_simulates_with_parity(captured):
    # the "downscaled copy": same capture, small machine — golden vs JAX
    # engine bit-exact on a real program's trace, locks and barriers
    # included
    cfg = MachineConfig(
        n_cores=captured.n_cores,
        n_banks=4,
        l1=CacheConfig(size=2048, ways=2, line=64, latency=2),
        llc=CacheConfig(size=16384, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=2, mesh_y=2, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=10_000,
    )
    assert_parity(cfg, captured, chunk_steps=64)


def test_capture_memops_off(ocean_binary):
    t = capture_run(
        [ocean_binary, "2", "1", "1"], capture_memops=False
    )
    types = t.events[:, :, 0]
    assert ((types == EV_LD) | (types == EV_ST)).sum() == 0
    assert (types == EV_BARRIER).sum() == 2  # sync still captured


def test_online_execution_driven_bit_exact(ocean_binary):
    """SURVEY.md §2 #9 / VERDICT r4 #4: the target streams events through
    the shared-memory ring while OnlineEngine simulates them CONCURRENTLY
    with its execution; results must be bit-exact with replaying the
    captured stream through the preloaded Engine."""
    from primesim_tpu.ingest.capture import capture_online
    from primesim_tpu.ingest.ring import OnlineEngine
    from primesim_tpu.sim.engine import Engine

    n_cores = N_THREADS + 1
    proc, src = capture_online(
        [ocean_binary, str(N_THREADS), str(N_PHASES), str(ROWS)],
        n_cores=n_cores,
        line=64,
    )
    try:
        cfg = MachineConfig(
            n_cores=n_cores,
            n_banks=4,
            l1=CacheConfig(size=2048, ways=2, line=64, latency=2),
            llc=CacheConfig(size=16384, ways=4, line=64, latency=10),
            noc=NocConfig(mesh_x=2, mesh_y=2, link_lat=1, router_lat=1),
            dram_lat=100,
            quantum=10_000,
        )
        eng = OnlineEngine(cfg, src, window_events=256)
        eng.run()  # returns only when the target finished and drained
        assert proc.wait(timeout=30) == 0
        assert src.dropped() == 0
        # replay the SAME stream (perf counts differ across runs, so the
        # equivalence claim is against this execution's trace)
        trace = src.to_trace()
        ref = Engine(cfg, trace, chunk_steps=64)
        ref.run()
        np.testing.assert_array_equal(eng.cycles, ref.cycles)
        rc = ref.counters
        for k, v in eng.counters.items():
            np.testing.assert_array_equal(v, rc[k], err_msg=k)
        # the whole point: events were being produced while we simulated
        assert int(src.total.sum()) > 0
    finally:
        if proc.poll() is None:
            proc.kill()
        src.close()
