"""Coarse sharer vector (Dir-G, cfg.sharer_group > 1) — SURVEY.md §2 #4,
BASELINE rung 5: full-map sharer storage at 16384 cores is 256 GiB, so
the wafer-scale rung runs group-granular bits. Hand-computed golden
semantics, golden-vs-engine bit-exact parity, and the conservatism
properties (no E grant while any bit is set; group-broadcast
invalidations)."""

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    CacheConfig,
    MachineConfig,
    NocConfig,
    small_test_config,
)
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, EV_ST, from_event_lists

from test_parity import assert_parity


def gcfg(n=8, G=4, **kw):
    kw.setdefault("n_banks", 4)
    kw.setdefault("quantum", 400)
    return small_test_config(n, sharer_group=G, **kw)


def test_sharer_words_shrink():
    assert gcfg(8, 4).n_sharer_words == 1
    assert MachineConfig(
        n_cores=16384, n_banks=4096, sharer_group=64,
        noc=NocConfig(mesh_x=128, mesh_y=128),
    ).n_sharer_words == 8  # 256 groups -> 8 words (full map needs 512)


def test_group_bit_covers_neighbors():
    # cores 0 and 1 share group 0 (G=4). Core 0 reads line 0 (E grant,
    # owner). Core 2 (group 0? no — core 2 also group 0 at G=4) reads ->
    # probe downgrades owner, sharers = {group 0}. A THIRD read from core
    # 1 (same group, bit already set) stays a plain S grant; and a write
    # from core 4 (group 1) must broadcast-invalidate ALL of group 0's
    # cores except itself: 4 recorded targets (cores 0-3) minus none.
    cfg = gcfg(8, 4)
    tr = from_event_lists(
        [
            [(EV_LD, 4, 0)],
            [(EV_LD, 4, 0)],
            [],
            [],
            [(EV_ST, 4, 0)],
            [],
            [],
            [],
        ]
    )
    g = GoldenSim(cfg, tr)
    g.run()
    # after the write: core 4 owns the line in M
    assert g.counters["invalidations"][4] == 4  # whole group 0 broadcast
    assert g.l1_state[4][0].max() == 3


def test_no_exclusive_grant_while_any_bit_set():
    # same-group cores 0,1 read the same line sequentially; core 1's
    # GETS must see "shared" (its own group's bit covers core 0) and
    # grant S, not E — the conservatism that keeps coarse mode coherent
    cfg = gcfg(8, 4)
    tr = from_event_lists(
        [[(EV_LD, 4, 0), (EV_LD, 4, 0)], [(EV_LD, 4, 0)], [], [], [], [], [], []]
    )
    g = GoldenSim(cfg, tr)
    g.run()
    # core 0 was probed-downgraded or stayed owner? Core 0 read first (E
    # grant, owner). Core 1's read probes the owner -> both end S.
    S = 1
    assert g.l1_state[0][g.l1_tag[0] == 0].max() == S
    assert g.l1_state[1][g.l1_tag[1] == 0].max() == S
    # one miss per core; core 0's second read is an L1 hit
    assert g.counters["l1_read_misses"].sum() == 2


@pytest.mark.parametrize("G", [4, 32])
@pytest.mark.parametrize(
    "gen", ["false_sharing", "uniform_random", "lock_contention"]
)
def test_parity_coarse(gen, G):
    cfg = gcfg(8, G)
    tr = {
        "false_sharing": lambda: synth.false_sharing(8, n_mem_ops=40, seed=31),
        "uniform_random": lambda: synth.uniform_random(8, n_mem_ops=50, seed=32),
        "lock_contention": lambda: synth.lock_contention(8, n_critical=8, seed=33),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=50)


def test_parity_coarse_64core_hot_lines():
    # 64 cores, 16 groups of 4, heavy sharing: group broadcasts, owner
    # re-recording, back-invalidations — engine bit-exact vs golden
    cfg = MachineConfig(
        n_cores=64, n_banks=16,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=4, mesh_y=4),
        quantum=500, sharer_group=4,
    )
    rng = np.random.default_rng(7)
    evs = []
    for c in range(64):
        core = []
        for _ in range(24):
            line = int(rng.integers(0, 12))
            t = EV_ST if rng.random() < 0.4 else EV_LD
            core.append((t, 2, line * 64))
        evs.append(core)
    assert_parity(cfg, from_event_lists(evs), chunk_steps=32)


def test_parity_coarse_with_local_runs():
    cfg = gcfg(8, 4, local_run_len=4)
    from primesim_tpu.trace.format import fold_ins

    tr = fold_ins(synth.fft_like(8, n_phases=2, points_per_core=12, seed=35))
    assert_parity(cfg, tr, chunk_steps=16)


def test_parity_coarse_with_router_and_dram_queue():
    # every round-5 timing model stacked on the coarse directory: hop-by
    # -hop router + controller queue + local runs + O3 — bit-exact
    from primesim_tpu.config.machine import CoreConfig, NocConfig
    from primesim_tpu.trace.format import fold_ins

    cfg = small_test_config(
        8, n_banks=8, quantum=500, local_run_len=4, sharer_group=4,
        dram_queue=True, dram_service=40,
        core=CoreConfig(o3_overlap_256=64),
        noc=NocConfig(mesh_x=4, mesh_y=2, contention=True,
                      contention_model="router"),
    )
    tr = fold_ins(synth.fft_like(8, n_phases=2, points_per_core=12, seed=36))
    assert_parity(cfg, tr, chunk_steps=16)
