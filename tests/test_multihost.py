"""REAL 2-process multi-host run (SURVEY.md §5.8, VERDICT r4 #6):
subprocess-spawn two CPU processes that `jax.distributed.initialize`
against a localhost coordinator, run the SAME sharded engine SPMD over
the global 2x2-device mesh, and assert the result is bit-exact with a
single-process run — turning `parallel/distributed.py` from API plumbing
into evidence (the reference's MPI multi-node runs, minus the cluster).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, os, sys
# platform env arrives via Popen env: the image's sitecustomize imports
# jax before this code runs, so in-process os.environ edits are too late
import jax
from primesim_tpu.parallel.distributed import (
    global_tile_mesh, init_multi_host, process_info,
)

coord, nproc, pid, out = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
init_multi_host(coord, nproc, pid)
info = process_info()
assert info["process_count"] == nproc, info
assert info["global_devices"] == 2 * nproc, info

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.sim.engine import Engine
from primesim_tpu.trace import synth

cfg = small_test_config(8, n_banks=8, quantum=400)
tr = synth.false_sharing(8, n_mem_ops=24, seed=77)
mesh = global_tile_mesh()
assert mesh.devices.size == 2 * nproc
eng = Engine(cfg, tr, chunk_steps=16, mesh=mesh)
eng.run()
# every process computes the same global result; process 0 reports
cycles = [int(x) for x in eng.cycles]
counters = {k: [int(x) for x in v] for k, v in eng.counters.items()}
if pid == 0:
    with open(out, "w") as f:
        json.dump({"cycles": cycles, "counters": counters, "info": info}, f)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.skip(
    reason="this image's jaxlib raises 'Multiprocess computations aren't "
    "implemented on the CPU backend' from device_put inside the 2-process "
    "SPMD run (XlaRuntimeError, jax.experimental.multihost_utils."
    "broadcast_one_to_all) — the distributed CPU client initializes and "
    "forms the global 2x2 mesh but cannot execute cross-process "
    "collectives, so the acceptance run needs a backend with real "
    "multi-process support (TPU pod / GPU cluster). The single-process "
    "mesh coverage in test_pod_scale/test_multichip keeps the sharding "
    "logic under test."
)
@pytest.mark.timeout(300)
def test_two_process_spmd_bit_exact(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    out = str(tmp_path / "result.json")
    # strip the image's TPU-plugin bootstrap (sitecustomize registers the
    # remote-TPU PJRT plugin whenever PALLAS_AXON_POOL_IPS is set, which
    # would pin the workers to the single shared chip); each process then
    # contributes 2 virtual CPU devices -> global mesh of 4
    env = {
        k: v
        for k, v in os.environ.items()
        if not (k.startswith("PALLAS_AXON") or k.startswith("AXON_"))
    }
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, "2", str(pid), out],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    for p in procs:
        try:
            rc = p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if rc != 0:
            raise AssertionError(
                f"worker exited {rc}\nstderr:\n{p.stderr.read()[-4000:]}"
            )
    with open(out) as f:
        got = json.load(f)
    assert got["info"]["process_count"] == 2
    assert got["info"]["global_devices"] == 4
    assert got["info"]["local_devices"] == 2

    # single-process reference in THIS process (8 virtual devices is
    # fine: the result must not depend on the mesh at all)
    from primesim_tpu.config.machine import small_test_config
    from primesim_tpu.golden.sim import GoldenSim
    from primesim_tpu.trace import synth

    cfg = small_test_config(8, n_banks=8, quantum=400)
    tr = synth.false_sharing(8, n_mem_ops=24, seed=77)
    g = GoldenSim(cfg, tr)
    g.run()
    np.testing.assert_array_equal(np.asarray(got["cycles"]), g.cycles)
    for k, v in got["counters"].items():
        np.testing.assert_array_equal(np.asarray(v), g.counters[k], err_msg=k)
