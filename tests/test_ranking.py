"""Property tests for the sort-based segmented-rank primitive
(ops/ranking.py, ISSUE 6 tentpole): on every input shape the engine can
produce — duplicate arbitration keys, masked lanes/slots, real mesh XY
paths (including faulted-config geometries, whose ranking walk stays on
the NOMINAL path by design), and fleet-vmapped batches — the sort path
must return the EXACT int32 counts of the historical one-hot-matmul
path it replaced (DESIGN.md §13 equivalence argument)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from primesim_tpu.config.machine import (
    FAULT_LINK_FAIL,
    NocConfig,
    small_test_config,
)
from primesim_tpu.noc.mesh import n_links, path_links
from primesim_tpu.ops.ranking import lane_order, segmented_rank


def matmul_oracle(seg, key, n_seg, competitor=None):
    """The replaced path, reference-shaped: [C,C] strict-less comparison
    contracted against the [C,n_seg] one-hot membership (duplicates in a
    lane's row collapse via `set(1)`), gathered back per slot."""
    seg = np.asarray(seg)
    key = np.asarray(key)
    C, S = seg.shape
    comp = np.ones(C, bool) if competitor is None else np.asarray(competitor)
    kless = (key[None, :] < key[:, None]) & comp[None, :]
    U = np.zeros((C, n_seg + 1), np.int32)
    U[np.arange(C)[:, None], np.clip(seg, 0, n_seg)] = 1
    ranks = kless.astype(np.int32) @ U  # [C, n_seg + 1]
    out = np.take_along_axis(ranks, np.clip(seg, 0, n_seg), axis=1)
    return out  # valid wherever seg < n_seg


def _unique_segs(rng, C, S, n_seg, mask_p=0.4):
    """Per-lane DISTINCT segment ids (the engine contract: one entry per
    (lane, segment)), with a random fraction masked to the sentinel."""
    seg = np.stack(
        [rng.choice(n_seg, size=S, replace=False) for _ in range(C)]
    ).astype(np.int32)
    return np.where(rng.random((C, S)) < mask_p, n_seg, seg).astype(np.int32)


@pytest.mark.parametrize("method", ["packed", "lex"])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_matches_oracle(method, seed):
    rng = np.random.default_rng(seed)
    C, S, n_seg = 64, 9, 37
    seg = _unique_segs(rng, C, S, n_seg)
    key = rng.integers(0, 500, C).astype(np.int32)  # dense => duplicates
    got = np.asarray(
        segmented_rank(jnp.asarray(seg), jnp.asarray(key), n_seg,
                       method=method)
    )
    want = matmul_oracle(seg, key, n_seg)
    valid = seg < n_seg
    np.testing.assert_array_equal(got[valid], want[valid])


def test_duplicate_keys_never_count_each_other():
    # every lane shares ONE key: all ranks must be zero (strict <)
    C, S, n_seg = 16, 4, 8
    rng = np.random.default_rng(7)
    seg = _unique_segs(rng, C, S, n_seg, mask_p=0.0)
    key = np.full(C, 42, np.int32)
    got = np.asarray(segmented_rank(jnp.asarray(seg), jnp.asarray(key), n_seg))
    np.testing.assert_array_equal(got, np.zeros((C, S), np.int32))


def test_masked_lanes_via_sentinel():
    # lanes that don't compete are masked by writing the sentinel into
    # EVERY slot (the engine's tgt_all = where(ok, path, NL) idiom):
    # they must neither receive real ranks nor count as competitors
    rng = np.random.default_rng(11)
    C, S, n_seg = 32, 5, 19
    seg = _unique_segs(rng, C, S, n_seg, mask_p=0.2)
    key = rng.integers(0, 10_000, C).astype(np.int32)
    competing = rng.random(C) < 0.6
    seg_masked = np.where(competing[:, None], seg, n_seg).astype(np.int32)
    got = np.asarray(
        segmented_rank(jnp.asarray(seg_masked), jnp.asarray(key), n_seg)
    )
    want = matmul_oracle(seg_masked, key, n_seg, competitor=competing)
    valid = seg_masked < n_seg
    np.testing.assert_array_equal(got[valid], want[valid])


@pytest.mark.parametrize("mesh", [(2, 2), (4, 4), (3, 2)])
def test_engine_shaped_mesh_paths(mesh):
    # real router-block shapes: concatenated request/reply XY legs over
    # random (core tile, bank tile) pairs — reversed DIRECTED links, so
    # the per-(lane, segment) uniqueness contract holds by construction
    mx, my = mesh
    cfg = small_test_config(
        mx * my * 2, n_banks=8,
        noc=NocConfig(mesh_x=mx, mesh_y=my, link_lat=1, router_lat=1),
    )
    C = cfg.n_cores
    NL = n_links(cfg)
    rng = np.random.default_rng(mx * 10 + my)
    ctile = jnp.asarray(np.arange(C) % cfg.n_tiles, jnp.int32)
    btile = jnp.asarray(rng.integers(0, cfg.n_tiles, C), jnp.int32)
    req_p = path_links(cfg, ctile, btile)
    rep_p = path_links(cfg, btile, ctile)
    txn = rng.random(C) < 0.7
    pth = np.concatenate([np.asarray(req_p), np.asarray(rep_p)], axis=1)
    ok = txn[:, None] & (pth >= 0)
    seg = np.where(ok, pth, NL).astype(np.int32)
    key = ((rng.integers(0, 50, C) * C) + np.arange(C)).astype(np.int32)
    got = np.asarray(segmented_rank(jnp.asarray(seg), jnp.asarray(key), NL))
    want = matmul_oracle(seg, key, NL, competitor=txn)
    np.testing.assert_array_equal(got[ok], want[ok])


def test_faulted_detour_config_paths_stay_nominal_and_exact():
    # fault-injection reroutes add latency AFTER the contention walk;
    # the ranking itself always runs on the NOMINAL XY paths.  A config
    # with link faults armed must therefore produce identical path sets
    # — and identical sort-vs-matmul ranks — as the clean config.
    cfg = small_test_config(8, n_banks=8)
    cfg_f = small_test_config(
        8, n_banks=8, faults_enabled=True, max_fault_events=1,
        fault_events=((0, FAULT_LINK_FAIL, 1, 0),), fault_seed=123,
    )
    C, NL = cfg.n_cores, n_links(cfg)
    rng = np.random.default_rng(5)
    ctile = jnp.asarray(np.arange(C) % cfg.n_tiles, jnp.int32)
    btile = jnp.asarray(rng.integers(0, cfg.n_tiles, C), jnp.int32)
    p_clean = np.asarray(path_links(cfg, ctile, btile))
    p_fault = np.asarray(path_links(cfg_f, ctile, btile))
    np.testing.assert_array_equal(p_clean, p_fault)
    seg = np.where(p_clean >= 0, p_clean, NL).astype(np.int32)
    key = np.arange(C, 0, -1).astype(np.int32)
    got = np.asarray(segmented_rank(jnp.asarray(seg), jnp.asarray(key), NL))
    want = matmul_oracle(seg, key, NL)
    valid = seg < NL
    np.testing.assert_array_equal(got[valid], want[valid])


def test_fleet_vmapped_batches_match_solo():
    # the fleet engine vmaps the whole step: a batched segmented_rank
    # must equal per-element calls bit-for-bit
    rng = np.random.default_rng(21)
    B, C, S, n_seg = 4, 24, 6, 15
    segs = np.stack([_unique_segs(rng, C, S, n_seg) for _ in range(B)])
    keys = rng.integers(0, 200, (B, C)).astype(np.int32)
    batched = np.asarray(
        jax.vmap(lambda s, k: segmented_rank(s, k, n_seg))(
            jnp.asarray(segs), jnp.asarray(keys)
        )
    )
    for b in range(B):
        solo = np.asarray(
            segmented_rank(jnp.asarray(segs[b]), jnp.asarray(keys[b]), n_seg)
        )
        np.testing.assert_array_equal(batched[b], solo, err_msg=f"elem {b}")


def test_lane_order_properties():
    key = jnp.asarray([5, 1, 5, 0, 9, 1], jnp.int32)
    got = np.asarray(lane_order(key))
    np.testing.assert_array_equal(got, [3, 1, 3, 0, 5, 1])
    # strict-comparison agreement on random data incl. duplicates
    rng = np.random.default_rng(3)
    k = rng.integers(0, 30, 100).astype(np.int32)
    o = np.asarray(lane_order(jnp.asarray(k)))
    np.testing.assert_array_equal(
        k[None, :] < k[:, None], o[None, :] < o[:, None]
    )


def test_precomputed_order_shared_across_calls():
    rng = np.random.default_rng(9)
    C, n_seg = 32, 12
    key = rng.integers(0, 100, C).astype(np.int32)
    seg = _unique_segs(rng, C, 4, n_seg)
    ordr = lane_order(jnp.asarray(key))
    a = segmented_rank(jnp.asarray(seg), jnp.asarray(key), n_seg)
    b = segmented_rank(jnp.asarray(seg), n_seg=n_seg, order=ordr)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_packed_and_lex_agree_on_engine_scale():
    rng = np.random.default_rng(17)
    C, S, n_seg = 128, 12, 257
    seg = _unique_segs(rng, C, S, n_seg)
    key = rng.integers(0, 1 << 20, C).astype(np.int32)
    a = np.asarray(segmented_rank(jnp.asarray(seg), jnp.asarray(key), n_seg,
                                  method="packed"))
    b = np.asarray(segmented_rank(jnp.asarray(seg), jnp.asarray(key), n_seg,
                                  method="lex"))
    valid = seg < n_seg
    np.testing.assert_array_equal(a[valid], b[valid])
