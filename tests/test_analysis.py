"""Analysis subsystem (ISSUE 12): lint rules on good/bad fixtures,
suppression + baseline semantics, fsck over clean/torn/tampered durable
state, the recompile sentinel, and the CLI's structured exit-2 contract
for AnalysisError/FsckCorrupt."""

import json
import os

import numpy as np
import pytest

from primesim_tpu.analysis.errors import (
    AnalysisError,
    FsckCorrupt,
    RecompileError,
)
from primesim_tpu.analysis.fsck import run_fsck
from primesim_tpu.analysis.lint import run_lint
from primesim_tpu.analysis.recompile import recompile_sentinel
from primesim_tpu.serve.journal import JobJournal, _frame

# ---- lint fixtures ------------------------------------------------------


def _lint(tmp_path, relpath, src, select=None):
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return run_lint(
        paths=[str(tmp_path)], root=str(tmp_path),
        baseline_path=str(tmp_path / "absent_baseline.json"),
        select=select,
    )


def _rules_of(res):
    return sorted({f.rule for f in res.findings})


def test_traced_branch_bad_and_good(tmp_path):
    bad = (
        "def f(st):\n"
        "    if st.knobs.cpi > 1:\n"
        "        return 1\n"
        "    while st.faults.due_rate:\n"
        "        pass\n"
        "    return float(st.knobs.dram_lat)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/x.py", bad,
                select=["PT-TRACED-BRANCH"])
    assert len(res.findings) == 3
    assert _rules_of(res) == ["PT-TRACED-BRANCH"]
    good = (
        "import jax.numpy as jnp\n"
        "def f(st, cfg):\n"
        "    y = jnp.where(st.knobs.cpi > 1, 1, 0)\n"
        "    if cfg.fault_seed:\n"  # config field, not a traced leaf
        "        y = y + 1\n"
        "    return y\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/x.py", good,
                select=["PT-TRACED-BRANCH"])
    assert res.clean


def test_traced_branch_out_of_scope_silent(tmp_path):
    # same code in stats/ (host-side folding) is not in the rule's scope
    bad = "def f(st):\n    return bool(st.knobs.cpi)\n"
    res = _lint(tmp_path, "primesim_tpu/stats/x.py", bad,
                select=["PT-TRACED-BRANCH"])
    assert res.clean


def test_jit_key_bad_and_good(tmp_path):
    bad = (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, static_argnames=('quantum',))\n"
        "def f(quantum):\n"
        "    return quantum\n"
        "from jax import jit\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/y.py", bad,
                select=["PT-JIT-KEY"])
    msgs = "\n".join(f.message for f in res.findings)
    assert "jax.jit site" in msgs
    assert "static_argnames" in msgs  # the knob-derived static name
    assert "from jax import jit" in msgs or "hides jit sites" in msgs
    assert len(res.findings) == 3
    good = "import jax.numpy as jnp\ndef f(x):\n    return jnp.sum(x)\n"
    res = _lint(tmp_path, "primesim_tpu/sim/y.py", good,
                select=["PT-JIT-KEY"])
    assert res.clean


def test_mosaic_bad_and_good(tmp_path):
    bad = (
        "import jax.numpy as jnp\n"
        "def kern(pl, x):\n"
        "    core = pl.program_id(0)\n"
        "    idx = jnp.nonzero(x)\n"
        "    return core, idx, jnp.where(x > 0)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/kernels/k.py", bad,
                select=["PT-MOSAIC"])
    assert len(res.findings) == 3
    good = (
        "import jax.numpy as jnp\n"
        "def kern(core_ids, x):\n"
        "    return jnp.where(core_ids > 0, x, 0)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/kernels/k.py", good,
                select=["PT-MOSAIC"])
    assert res.clean
    # dynamic-shape ops ARE the layouts.py idiom (host-side planning)
    res = _lint(tmp_path, "primesim_tpu/kernels/layouts.py",
                "import numpy as np\ndef plan(x):\n"
                "    return np.nonzero(x)\n",
                select=["PT-MOSAIC"])
    assert res.clean


def test_durable_shared_tmp_regression_pr10(tmp_path):
    # the exact PR 10 bug shape: deterministic shared temp name + raw
    # write-mode open on a checkpoint path
    bad = (
        "import os, json\n"
        "def save_meta(meta_path, meta):\n"
        "    tmp = meta_path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(meta, f)\n"
        "    os.replace(tmp, meta_path)\n"
        "def save_meta2(meta_path, meta):\n"
        "    tmp = f'{meta_path}.tmp'\n"
        "    return tmp\n"
    )
    res = _lint(tmp_path, "primesim_tpu/serve/w.py", bad,
                select=["PT-DURABLE"])
    assert len(res.findings) == 3  # BinOp .tmp, open 'w', f-string .tmp
    good = (
        "import os, json, tempfile\n"
        "def save_meta(root, meta_path, meta):\n"
        "    fd, tmp = tempfile.mkstemp(dir=root, suffix='.tmp')\n"
        "    with os.fdopen(fd, 'w') as f:\n"
        "        json.dump(meta, f)\n"
        "    os.replace(tmp, meta_path)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/serve/w.py", good,
                select=["PT-DURABLE"])
    assert res.clean


def test_typed_err_bad_and_good(tmp_path):
    bad = "def f():\n    raise ValueError('nope')\n"
    res = _lint(tmp_path, "primesim_tpu/cli/z.py", bad,
                select=["PT-TYPED-ERR"])
    assert len(res.findings) == 1
    good = (
        "class SpecError(ValueError):\n"
        "    def location(self):\n"
        "        return {}\n"
        "def f():\n"
        "    raise SpecError('typed')\n"
    )
    res = _lint(tmp_path, "primesim_tpu/cli/z.py", good,
                select=["PT-TYPED-ERR"])
    assert res.clean


def test_obs_hook_bad_and_good(tmp_path):
    bad = (
        "class E:\n"
        "    def step(self):\n"
        "        self.obs.chunk_committed(1)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/o.py", bad,
                select=["PT-OBS-HOOK"])
    assert len(res.findings) == 1
    good = (
        "class E:\n"
        "    def step(self):\n"
        "        if self.obs is None:\n"
        "            return\n"
        "        self.obs.chunk_committed(1)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/o.py", good,
                select=["PT-OBS-HOOK"])
    assert res.clean


def test_suppression_comment(tmp_path):
    src = (
        "def f(st):\n"
        "    return bool(st.knobs.cpi)  # ptlint: allow=PT-TRACED-BRANCH\n"
        "def g(st):\n"
        "    # ptlint: allow=*\n"
        "    return bool(st.knobs.cpi)\n"
    )
    res = _lint(tmp_path, "primesim_tpu/sim/s.py", src,
                select=["PT-TRACED-BRANCH"])
    assert res.clean and res.suppressed == 2


def test_baseline_count_and_stale(tmp_path):
    src = (
        "def f():\n"
        "    raise ValueError('nope')\n"
        "def g():\n"
        "    raise ValueError('nope')\n"
    )
    p = tmp_path / "primesim_tpu/cli/z.py"
    p.parent.mkdir(parents=True)
    p.write_text(src)
    bl = tmp_path / "LINT_BASELINE.json"

    def run(entries):
        bl.write_text(json.dumps({"entries": entries}))
        return run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                        baseline_path=str(bl), select=["PT-TYPED-ERR"])

    entry = {"rule": "PT-TYPED-ERR", "path": "primesim_tpu/cli/z.py",
             "line_text": "raise ValueError('nope')", "why": "test"}
    # count=1 absorbs one of the two identical findings
    res = run([dict(entry, count=1)])
    assert len(res.findings) == 1 and res.baselined == 1
    # count=2 absorbs both
    res = run([dict(entry, count=2)])
    assert res.clean and res.baselined == 2
    # an entry matching nothing is reported stale (debt already paid)
    res = run([dict(entry, count=2),
               dict(entry, line_text="raise ValueError('gone')",
                    count=1)])
    assert res.clean and len(res.stale) == 1


def test_baseline_malformed_raises(tmp_path):
    bl = tmp_path / "LINT_BASELINE.json"
    bl.write_text("{not json")
    with pytest.raises(AnalysisError):
        run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                 baseline_path=str(bl))
    bl.write_text(json.dumps({"entries": [{"rule": "PT-X"}]}))
    with pytest.raises(AnalysisError):
        run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                 baseline_path=str(bl))


def test_unknown_rule_select_raises(tmp_path):
    with pytest.raises(AnalysisError):
        run_lint(paths=[str(tmp_path)], root=str(tmp_path),
                 select=["PT-NOPE"])


def test_traced_field_mirror_in_sync():
    # rules.py mirrors the pytree field names so linting never imports
    # jax; this test is the tripwire that keeps the mirror honest
    from primesim_tpu.analysis import rules
    from primesim_tpu.faults.schedule import FaultState
    from primesim_tpu.sim.state import TimingKnobs

    assert rules.KNOB_FIELDS == frozenset(TimingKnobs._fields)
    assert rules.FAULT_FIELDS == frozenset(FaultState._fields)


def test_repo_lints_clean():
    # the S1 acceptance bar: the shipped tree + committed baseline has
    # zero findings (new debt must be fixed or explicitly baselined)
    res = run_lint()
    assert res.clean, "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in res.findings
    )
    assert not res.stale, res.stale


# ---- fsck: journals -----------------------------------------------------


def _serve_journal(d, n_jobs=4, segment_records=3):
    j = JobJournal(str(d), segment_records=segment_records)
    for i in range(n_jobs):
        j.append({"t": "accept",
                  "job": {"job_id": f"j{i}", "synth": "stream:n_mem_ops=5"}})
        j.append({"t": "state", "job_id": f"j{i}", "state": "RUNNING"})
        j.append({"t": "state", "job_id": f"j{i}", "state": "DONE",
                  "result": {"x": i}})
    j.close()
    return d


def test_fsck_clean_journal(tmp_path):
    _serve_journal(tmp_path / "sj")
    res = run_fsck(str(tmp_path))
    assert res.clean and not res.findings
    assert res.checked["journals"] == 1 and res.checked["records"] == 12


def test_fsck_torn_tail_is_a_note_not_corruption(tmp_path):
    _serve_journal(tmp_path / "sj")
    with open(tmp_path / "sj" / "journal.jsonl", "a") as f:
        f.write('{"c": 1, "r": {"t":"state","job_id"')  # torn append
    res = run_fsck(str(tmp_path))
    assert res.clean  # kill -9 debris: replay drops it, fsck exits 0
    assert len(res.findings) == 1 and "torn tail" in res.findings[0].detail


def test_fsck_closed_segment_rot(tmp_path):
    _serve_journal(tmp_path / "sj")
    segs = sorted(p for p in os.listdir(tmp_path / "sj")
                  if p.startswith("journal-"))
    sp = tmp_path / "sj" / segs[0]
    b = sp.read_bytes()
    sp.write_bytes(b[:40] + bytes([b[40] ^ 0xFF]) + b[41:])
    res = run_fsck(str(tmp_path))
    assert any(f.kind == "journal-record" and f.corrupt
               for f in res.findings)


def test_fsck_tampered_segment_chain(tmp_path):
    _serve_journal(tmp_path / "sj", n_jobs=5, segment_records=2)
    segs = sorted(p for p in os.listdir(tmp_path / "sj")
                  if p.startswith("journal-"))
    sp = tmp_path / "sj" / segs[1]
    # rewrite a middle segment with VALID frames but different content:
    # per-line CRCs pass, so only the next segment's prev back-link can
    # catch the transplant
    header = json.loads(sp.read_text().splitlines()[0])["r"]
    sp.write_text(_frame(header) + "\n"
                  + _frame({"t": "note", "msg": "tampered"}) + "\n")
    res = run_fsck(str(tmp_path))
    assert any("back-link" in f.detail for f in res.corrupt)


def test_fsck_missing_middle_segment(tmp_path):
    _serve_journal(tmp_path / "sj", n_jobs=5, segment_records=2)
    segs = sorted(p for p in os.listdir(tmp_path / "sj")
                  if p.startswith("journal-"))
    os.remove(tmp_path / "sj" / segs[1])
    res = run_fsck(str(tmp_path))
    assert any("missing from the chain" in f.detail for f in res.corrupt)


def test_fsck_illegal_job_transition(tmp_path):
    j = JobJournal(str(tmp_path / "sj"), segment_records=None)
    j.append({"t": "accept", "job": {"job_id": "ja", "synth": "s"}})
    j.append({"t": "state", "job_id": "ja", "state": "DONE"})  # skip RUN
    # tolerated shapes must NOT fire: post-terminal echo + crash requeue
    j.append({"t": "state", "job_id": "ja", "state": "RUNNING"})
    j.append({"t": "accept", "job": {"job_id": "jb", "synth": "s"}})
    j.append({"t": "state", "job_id": "jb", "state": "RUNNING"})
    j.append({"t": "state", "job_id": "jb", "state": "PENDING"})
    j.append({"t": "state", "job_id": "jb", "state": "RUNNING"})
    j.close()
    res = run_fsck(str(tmp_path))
    bad = [f for f in res.corrupt if f.kind == "job-transition"]
    assert len(bad) == 1 and "PENDING -> DONE" in bad[0].detail


def test_fsck_state_without_accept(tmp_path):
    j = JobJournal(str(tmp_path / "sj"), segment_records=None)
    j.append({"t": "state", "job_id": "ghost", "state": "RUNNING"})
    j.close()
    res = run_fsck(str(tmp_path))
    assert any("no accept record" in f.detail for f in res.corrupt)


def test_fsck_pool_unit_key_consistency(tmp_path):
    from primesim_tpu.pool.units import unit_key

    spec = {"unit_id": "u1", "index": 0, "config": "{}", "synth": "s",
            "trace_path": None, "fold": True, "overrides": {},
            "chunk_steps": 16, "max_steps": 100}
    spec["key"] = unit_key(spec)
    # clean ledger passes
    p = JobJournal(str(tmp_path / "ok"), segment_records=None)
    p.append({"t": "unit", "unit": dict(spec)})
    p.append({"t": "lease", "unit_id": "u1", "worker": "w", "epoch": 1,
              "key": spec["key"]})
    p.append({"t": "ack", "unit_id": "u1", "worker": "w", "epoch": 1,
              "key": spec["key"], "result": {}})
    p.close()
    assert run_fsck(str(tmp_path / "ok")).clean
    # conflicting lease key fails
    p = JobJournal(str(tmp_path / "bad"), segment_records=None)
    p.append({"t": "unit", "unit": dict(spec)})
    p.append({"t": "lease", "unit_id": "u1", "worker": "w", "epoch": 1,
              "key": "deadbeefdeadbeef"})
    p.close()
    res = run_fsck(str(tmp_path / "bad"))
    assert any("conflicting unit keys" in f.detail for f in res.corrupt)
    # edited spec: content no longer hashes to its stamped key
    p = JobJournal(str(tmp_path / "edit"), segment_records=None)
    edited = dict(spec, max_steps=999_999)
    p.append({"t": "unit", "unit": edited})
    p.close()
    res = run_fsck(str(tmp_path / "edit"))
    assert any("stamped key" in f.detail for f in res.corrupt)


# ---- fsck: checkpoints + warm cache ------------------------------------


def _solo_npz(path, rows=None):
    from primesim_tpu.sim.checkpoint import _FORMAT, atomic_save_npz
    from primesim_tpu.stats.counters import COUNTER_NAMES

    atomic_save_npz(
        str(path),
        format=np.int64(_FORMAT),
        cycle_base=np.int64(0),
        steps_run=np.int64(0),
        config_json=np.frombuffer(b"{}", dtype=np.uint8),
        trace_sha=np.frombuffer(b"ab" * 32, dtype=np.uint8),
        state_counters=np.zeros(
            (rows if rows is not None else len(COUNTER_NAMES), 4),
            np.int32,
        ),
    )


def test_fsck_checkpoint_crc_tamper(tmp_path):
    _solo_npz(tmp_path / "ck.npz")
    assert run_fsck(str(tmp_path)).clean
    b = (tmp_path / "ck.npz").read_bytes()
    (tmp_path / "ck.npz").write_bytes(
        b[:len(b) // 2] + bytes([b[len(b) // 2] ^ 0xFF])
        + b[len(b) // 2 + 1:]
    )
    res = run_fsck(str(tmp_path))
    assert any(f.kind == "checkpoint" for f in res.corrupt)


def test_fsck_checkpoint_counter_rows(tmp_path):
    _solo_npz(tmp_path / "ck.npz", rows=3)
    res = run_fsck(str(tmp_path))
    assert any("counter rows" in f.detail for f in res.corrupt)


def test_fsck_warm_entry_and_sidecar(tmp_path):
    from primesim_tpu.sim.checkpoint import _FORMAT, atomic_save_npz
    from primesim_tpu.stats.counters import COUNTER_NAMES

    key = "ab" * 32
    atomic_save_npz(
        str(tmp_path / f"{key}.npz"),
        format=np.int64(_FORMAT), warm=np.int64(1),
        steps=np.int64(512), cycle_base=np.int64(0),
        steps_run=np.int64(512),
        trace_sha=np.frombuffer(b"cd" * 32, dtype=np.uint8),
        state_counters=np.zeros((len(COUNTER_NAMES), 4), np.int32),
        host_counters=np.zeros((len(COUNTER_NAMES), 4), np.int64),
    )
    meta = {"cfg_key": "ef" * 32, "key": key, "trace_sha": "cd" * 32,
            "steps": 512}
    (tmp_path / f"{key}.json").write_text(json.dumps(meta))
    assert run_fsck(str(tmp_path)).clean
    # sidecar claiming different steps = key/content disagreement
    (tmp_path / f"{key}.json").write_text(
        json.dumps(dict(meta, steps=1024))
    )
    res = run_fsck(str(tmp_path))
    assert any("steps" in f.detail for f in res.corrupt)
    # orphan sidecar (npz pruned) is a note, not corruption
    os.remove(tmp_path / f"{key}.npz")
    (tmp_path / f"{key}.json").write_text(json.dumps(meta))
    res = run_fsck(str(tmp_path))
    assert res.clean and any(f.kind == "orphan" for f in res.findings)


def test_fsck_quarantine_moves_never_deletes(tmp_path):
    (tmp_path / "ck.npz").write_bytes(b"garbage, not a zip")
    (tmp_path / "leftover.npz.k3j2.tmp").write_bytes(b"partial")
    res = run_fsck(str(tmp_path), repair="quarantine")
    assert sorted(res.quarantined) == [
        "ck.npz", "leftover.npz.k3j2.tmp"
    ]
    q = tmp_path / ".fsck-quarantine"
    assert (q / "ck.npz").read_bytes() == b"garbage, not a zip"
    assert (q / "leftover.npz.k3j2.tmp").exists()
    assert not (tmp_path / "ck.npz").exists()
    # quarantined files are not re-scanned
    assert run_fsck(str(tmp_path)).clean


# ---- recompile sentinel -------------------------------------------------


def test_recompile_sentinel_allows_one_compile():
    from primesim_tpu.config.machine import small_test_config
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.trace import synth

    cfg = small_test_config(4, n_banks=4)
    tr = synth.stream(4, n_mem_ops=10, seed=7)
    with recompile_sentinel(allowed=1, watch=("engine",),
                            label="fresh geometry") as s:
        Engine(cfg, tr, chunk_steps=8).run()
    assert s.active
    assert all(g <= 1 for g in s.growth().values())
    # warm re-run compiles nothing
    with recompile_sentinel(allowed=0, watch=("engine",)) as s:
        Engine(cfg, tr, chunk_steps=8).run()
    assert all(g == 0 for g in s.growth().values())


def test_recompile_sentinel_raises_on_breach():
    from primesim_tpu.config.machine import small_test_config
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.trace import synth

    cfg = small_test_config(4, n_banks=4)
    tr = synth.stream(4, n_mem_ops=10, seed=8)
    Engine(cfg, tr, chunk_steps=8).run()  # warm this geometry
    with pytest.raises(RecompileError) as ei:
        with recompile_sentinel(allowed=0, watch=("engine",),
                                label="guard"):
            # a NEW chunk size is a new static key -> forced compile
            Engine(cfg, tr, chunk_steps=16).run()
    assert any(g > 0 for g in ei.value.growth.values())
    assert "location" not in ei.value.location() or True
    assert ei.value.location()["growth"] == ei.value.growth


def test_recompile_sentinel_unknown_preset():
    with pytest.raises(RecompileError):
        with recompile_sentinel(watch=("gpu",)):
            pass


# ---- CLI contract (S6) --------------------------------------------------


def test_cli_lint_exit_codes(tmp_path, capsys):
    from primesim_tpu.cli import main

    bad = tmp_path / "primesim_tpu" / "cli" / "z.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f():\n    raise ValueError('nope')\n")
    rc = main(["lint", str(tmp_path), "--root", str(tmp_path),
               "--select", "PT-TYPED-ERR", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["summary"]["findings"] == 1
    assert out["findings"][0]["rule"] == "PT-TYPED-ERR"
    bad.write_text("def f():\n    return 0\n")
    rc = main(["lint", str(tmp_path), "--root", str(tmp_path),
               "--select", "PT-TYPED-ERR"])
    assert rc == 0


def test_cli_lint_analysis_error_is_structured(tmp_path, capsys):
    from primesim_tpu.cli import main

    bl = tmp_path / "LINT_BASELINE.json"
    bl.write_text("{not json")
    rc = main(["lint", str(tmp_path), "--root", str(tmp_path),
               "--baseline", str(bl)])
    err = capsys.readouterr().err.strip().splitlines()[-1]
    obj = json.loads(err)
    assert rc == 2 and obj["error"]["type"] == "AnalysisError"
    assert obj["error"]["location"]["path"] == str(bl)


def test_cli_fsck_exit_2_structured_on_tamper(tmp_path, capsys):
    from primesim_tpu.cli import main

    _serve_journal(tmp_path / "sj")
    segs = sorted(p for p in os.listdir(tmp_path / "sj")
                  if p.startswith("journal-"))
    sp = tmp_path / "sj" / segs[0]
    b = sp.read_bytes()
    sp.write_bytes(b[:40] + bytes([b[40] ^ 0xFF]) + b[41:])
    rc = main(["fsck", str(tmp_path), "--format", "json"])
    cap = capsys.readouterr()
    assert rc == 2
    obj = json.loads(cap.err.strip().splitlines()[-1])
    assert obj["error"]["type"] == "FsckCorrupt"
    assert obj["error"]["location"]["n_corrupt"] >= 1
    # the json report still went to stdout before the error
    rep = json.loads(cap.out)
    assert rep["summary"]["corrupt"] >= 1


def test_cli_fsck_clean_exit_0(tmp_path, capsys):
    from primesim_tpu.cli import main

    _serve_journal(tmp_path / "sj")
    rc = main(["fsck", str(tmp_path)])
    assert rc == 0
    assert "0 corrupt" in capsys.readouterr().out
