"""Pod-scale composition: shard x vmap fleets + the pipelined rung-5
path (ISSUE 16 tentpole, DESIGN.md §22).

The contracts under test:

- `FleetEngine(..., mesh=...)` lays every element's MachineState out with
  the solo `state_pspecs()` under the batch vmap, and per-element results
  are BIT-EXACT vs the unsharded fleet (and, transitively, vs a solo
  Engine) — across knob sweeps, fault injection, prefix forking, and
  checkpoint kill -> resume.
- `state_pspecs()` is a TRIPWIRE for MachineState: adding a state field
  without deciding its partitioning fails here, not as a silent
  replication regression on a real pod.
- the ingest pipeline (segments -> SegmentSpool -> PipelineStreamEngine)
  assembles windows byte-identical to the plain StreamEngine fill, so
  pipelined runs are bit-exact; `--devices N` on a CLI sweep is bit-exact
  with `--devices 0`; bad mesh shapes exit 2 with one structured
  {"error": ...} line.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    FAULT_CORE_FAILSTOP,
    MachineConfig,
    small_test_config,
)
from primesim_tpu.parallel.sharding import (
    AXIS,
    DeviceMeshError,
    fleet_events_pspec,
    fleet_state_pspecs,
    state_pspecs,
    tile_mesh,
    validate_devices,
)
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.fleet import FleetEngine, apply_overrides
from primesim_tpu.trace import synth

from test_fleet import assert_element_matches_solo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHUNK = 16


def _cfg(n_cores=16, **kw):
    kw.setdefault("n_banks", 8)
    kw.setdefault("quantum", 200)
    return small_test_config(n_cores, **kw)


def _traces(n_cores=16):
    return [
        synth.false_sharing(n_cores, n_mem_ops=40, seed=11),
        synth.uniform_random(n_cores, n_mem_ops=60, seed=12),
        synth.lock_contention(n_cores, n_critical=6, seed=13),
        synth.fft_like(n_cores, n_phases=2, points_per_core=8, seed=14),
    ]


OVS = [
    {},
    {"llc_lat": 25, "dram_lat": 140, "l1_lat": 4},
    {"quantum": 150, "cpi": 2},
    {"link_lat": 3, "router_lat": 2},
]


def _assert_fleets_equal(a, b):
    np.testing.assert_array_equal(a.cycles, b.cycles)
    np.testing.assert_array_equal(a.steps_run, b.steps_run)
    for k, v in a.counters.items():
        np.testing.assert_array_equal(v, b.counters[k], err_msg=k)
    for f in a.state._fields:
        va, vb = getattr(a.state, f), getattr(b.state, f)
        if hasattr(va, "_fields"):
            for sub in va._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(va, sub)),
                    np.asarray(getattr(vb, sub)),
                    err_msg=f"state field {f}.{sub}",
                )
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=f"state field {f}"
        )


# ---- pspec <-> MachineState tripwire --------------------------------------


def test_state_pspecs_cover_machine_state_exactly():
    """Adding a MachineState (or TimingKnobs/FaultState) field without
    deciding its partitioning must fail HERE, not as a silently
    replicated array on a real pod."""
    import jax
    from jax.sharding import PartitionSpec as P

    from primesim_tpu.sim.state import init_state

    specs = state_pspecs()
    st = init_state(_cfg(8, n_banks=4))
    is_p = lambda x: isinstance(x, P)  # noqa: E731
    assert jax.tree.structure(specs, is_leaf=is_p) == jax.tree.structure(st)
    for spec in jax.tree.leaves(specs, is_leaf=is_p):
        assert isinstance(spec, P), f"{spec!r}: not a PartitionSpec"
    fspecs = fleet_state_pspecs()
    assert jax.tree.structure(fspecs, is_leaf=is_p) == jax.tree.structure(st)
    for spec in jax.tree.leaves(fspecs, is_leaf=is_p):
        assert isinstance(spec, P) and len(spec) >= 1, spec
        assert spec[0] is None, f"{spec!r}: batch axis must stay unsharded"
    assert tuple(fleet_events_pspec()) == (None, AXIS)


def test_state_pspecs_shard_the_core_and_bank_axes():
    specs = state_pspecs()
    assert tuple(specs.cycles) == (AXIS,)
    assert tuple(specs.dirm) == (AXIS,)
    assert tuple(specs.counters) == (None, AXIS)
    assert tuple(specs.faults.core_dead) == (AXIS,)


# ---- typed --devices validation -------------------------------------------


def test_validate_devices_typed_errors():
    cfg = _cfg(16, n_banks=8)
    validate_devices(cfg, 8)  # sound: divides both axes, 8 visible
    with pytest.raises(DeviceMeshError) as e:
        validate_devices(cfg, 5)
    assert e.value.location() == {"devices": 5, "visible": 8}
    with pytest.raises(DeviceMeshError) as e:
        validate_devices(cfg, 16)
    assert "visible" in str(e.value)
    with pytest.raises(DeviceMeshError):
        validate_devices(cfg, 0)
    # banks constrain too: 16 cores / 4 banks, devices=8 divides cores
    # but not banks
    with pytest.raises(DeviceMeshError) as e:
        validate_devices(_cfg(16, n_banks=4), 8)
    assert "n_banks" in str(e.value)


def test_cli_devices_errors_exit_2_with_structured_json(capsys):
    from primesim_tpu.cli import main

    cfg = os.path.join(REPO, "configs", "rung1_64core_fft.json")
    for args in (
        ["run", cfg, "--synth", "fft_like", "--devices", "5"],
        ["sweep", cfg, "--synth", "fft_like", "--devices", "48"],
    ):
        rc = main(args)
        assert rc == 2
        err = capsys.readouterr().err.strip().splitlines()[-1]
        obj = json.loads(err)
        assert obj["error"]["type"] == "DeviceMeshError"
        assert obj["error"]["location"]["devices"] in (5, 48)


# ---- sharded fleet parity (shard x vmap) ----------------------------------


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
@pytest.mark.parametrize("devices", [4, 8])
def test_sharded_fleet_bit_exact_vs_unsharded_and_solo(devices):
    cfg = _cfg()
    traces = _traces()
    plain = FleetEngine(cfg, traces, OVS, chunk_steps=CHUNK)
    plain.run()
    sharded = FleetEngine(
        cfg, traces, OVS, chunk_steps=CHUNK, mesh=tile_mesh(devices)
    )
    sharded.run()
    _assert_fleets_equal(sharded, plain)
    # spot-check one element against a solo Engine of the effective cfg
    assert_element_matches_solo(
        sharded, 1, apply_overrides(cfg, OVS[1]), traces[1],
        chunk_steps=CHUNK,
    )


def test_sharded_fleet_state_is_actually_sharded():
    import jax

    cfg = _cfg()
    fleet = FleetEngine(
        cfg, _traces(), OVS, chunk_steps=CHUNK, mesh=tile_mesh(8)
    )
    spec = fleet.state.cycles.sharding.spec
    assert tuple(spec) == (None, AXIS), spec
    assert tuple(fleet.events.sharding.spec)[:2] == (None, AXIS)
    assert len(fleet.state.cycles.sharding.mesh.devices.flat) == 8
    fleet.run()
    # outputs keep the layout (GSPMD propagation, no host gather mid-run)
    assert tuple(fleet.state.cycles.sharding.spec) == (None, AXIS)
    del jax


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_sharded_fleet_fault_injection_parity():
    cfg = dataclasses.replace(
        _cfg(),
        faults_enabled=True,
        max_fault_events=1,
        fault_events=((30, FAULT_CORE_FAILSTOP, 3, 0),),
    )
    traces = [_traces()[1]] * 3
    ovs = [{"fault_seed": 100 + i} for i in range(3)]
    plain = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    plain.run()
    sharded = FleetEngine(
        cfg, traces, ovs, chunk_steps=CHUNK, mesh=tile_mesh(8)
    )
    sharded.run()
    _assert_fleets_equal(sharded, plain)
    assert int(np.asarray(sharded.state.faults.core_dead).sum()) > 0


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_sharded_fleet_prefix_fork_parity():
    """Prefix forking mutates fleet slots host-side (fork_element); the
    sharded fleet must re-lay the state out and stay bit-exact."""
    from primesim_tpu.config.machine import FAULT_LINK_DEGRADE
    from primesim_tpu.sim.prefix import execute_prefix_plan, plan_prefix

    cfg = dataclasses.replace(
        _cfg(),
        faults_enabled=True,
        max_fault_events=1,
        fault_events=((40, FAULT_LINK_DEGRADE, 0, 3),),
    )
    tr = _traces()[3]
    ovs = [{"fault_seed": 7 + i} for i in range(4)]
    plain = FleetEngine(cfg, [tr] * 4, ovs, chunk_steps=CHUNK)
    plain.run()

    forked = FleetEngine(
        cfg, [tr] * 4, ovs, chunk_steps=CHUNK, mesh=tile_mesh(8)
    )
    groups = plan_prefix(forked.elem_cfgs, forked.traces, chunk_steps=CHUNK)
    assert groups and groups[0].prefix_steps > 0
    st = execute_prefix_plan(forked, groups)
    assert st["forked_elements"] == 4
    assert tuple(forked.state.cycles.sharding.spec) == (None, AXIS)
    forked.run()
    _assert_fleets_equal(forked, plain)


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_sharded_fleet_checkpoint_kill_resume_parity(tmp_path):
    from primesim_tpu.sim.checkpoint import (
        load_fleet_checkpoint,
        save_fleet_checkpoint,
    )

    cfg = _cfg()
    traces = _traces()
    plain = FleetEngine(cfg, traces, OVS, chunk_steps=CHUNK)
    plain.run()

    first = FleetEngine(
        cfg, traces, OVS, chunk_steps=CHUNK, mesh=tile_mesh(8)
    )
    first.run_steps(2 * CHUNK)  # mid-run cut, then the "crash"
    path = str(tmp_path / "fleet.npz")
    save_fleet_checkpoint(path, first)
    del first

    resumed = FleetEngine(
        cfg, traces, OVS, chunk_steps=CHUNK, mesh=tile_mesh(8)
    )
    load_fleet_checkpoint(path, resumed)
    assert tuple(resumed.state.cycles.sharding.spec) == (None, AXIS)
    resumed.run()
    _assert_fleets_equal(resumed, plain)


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_sharded_stream_engine_bit_exact(tmp_path):
    from primesim_tpu.ingest.stream import StreamEngine

    cfg = _cfg()
    tr = synth.fft_like(16, n_phases=2, points_per_core=12, seed=31)
    plain = StreamEngine(cfg, tr, window_events=32)
    plain.warmup()
    plain.run()
    sharded = StreamEngine(cfg, tr, window_events=32, mesh=tile_mesh(8))
    sharded.warmup()
    sharded.run()
    np.testing.assert_array_equal(sharded.cycles, plain.cycles)
    for k, v in plain.counters.items():
        np.testing.assert_array_equal(sharded.counters[k], v, err_msg=k)


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_cli_sweep_devices_bit_exact_vs_unsharded(capsys):
    from primesim_tpu.cli import main

    cfg_path = os.path.join(REPO, "configs", "rung1_64core_fft.json")
    base = [
        "sweep", cfg_path,
        "--synth", "fft_like:n_phases=2,points_per_core=8",
        "--vary", "llc_lat=10", "--vary", "llc_lat=20",
        "--chunk-steps", "64",
    ]

    def run(extra):
        assert main(base + extra) == 0
        lines = [
            json.loads(ln)
            for ln in capsys.readouterr().out.strip().splitlines()
        ]
        for d in lines:
            d["detail"].pop("wall_s", None)
            d["value"] = None  # MIPS embeds wall clock
        return lines

    assert run(["--devices", "8"]) == run([])


# ---- ingest pipeline (rung-5 stages) --------------------------------------


def test_segment_roundtrip_and_identity_check(tmp_path):
    from primesim_tpu.ingest.pipeline import (
        normalize_segment,
        read_segment,
        segment_path,
        write_segment,
    )

    cfg = _cfg(8, n_banks=4)
    tr = synth.uniform_random(8, n_mem_ops=50, seed=5)
    arr, n_valid = normalize_segment(cfg, tr, 0, 64)
    assert arr.shape == (8, 64, 4) and n_valid > 0
    p = segment_path(str(tmp_path), 0)
    write_segment(p, 0, 64, arr)
    np.testing.assert_array_equal(read_segment(p, 0, 64), arr)
    with pytest.raises(ValueError, match="identity"):
        read_segment(p, 1, 64)


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_pipeline_stream_engine_bit_exact_vs_plain(tmp_path):
    """Windows assembled from pre-normalized segments carry the same
    bytes as the plain host fill — results bit-exact, segments evicted
    as the cursors pass them."""
    from primesim_tpu.ingest.pipeline import (
        PipelineStreamEngine,
        SegmentSpool,
        normalize_segment,
        segment_path,
        write_segment,
    )
    from primesim_tpu.ingest.stream import StreamEngine

    cfg = _cfg(8, n_banks=4)
    tr = synth.lock_contention(8, n_critical=8, seed=6)  # ragged lengths
    L = 32
    real_max = int((np.asarray(tr.lengths) - 1).max())
    n_segments = -(-real_max // L)
    for k in range(n_segments):  # "ingest stage" ran ahead of the sim
        arr, _ = normalize_segment(cfg, tr, k, L)
        write_segment(segment_path(str(tmp_path), k), k, L, arr)

    plain = StreamEngine(cfg, tr, window_events=16)
    plain.warmup()
    plain.run()
    spool = SegmentSpool(str(tmp_path), L, n_segments, timeout_s=5.0)
    piped = PipelineStreamEngine(cfg, tr, spool, window_events=16)
    piped.warmup()
    piped.run()
    np.testing.assert_array_equal(piped.cycles, plain.cycles)
    for k, v in plain.counters.items():
        np.testing.assert_array_equal(piped.counters[k], v, err_msg=k)
    assert spool.waits == 0  # everything was resident: no stalls


def test_pipeline_spool_blocks_until_segment_appears(tmp_path):
    from primesim_tpu.ingest.pipeline import (
        SegmentSpool,
        normalize_segment,
        segment_path,
        write_segment,
    )

    cfg = _cfg(8, n_banks=4)
    tr = synth.uniform_random(8, n_mem_ops=40, seed=9)
    arr, _ = normalize_segment(cfg, tr, 0, 64)
    wrote = {"done": False}

    def late_ingest():  # the wait_cb plays the part of a slow stage 1
        if not wrote["done"]:
            wrote["done"] = True
            write_segment(segment_path(str(tmp_path), 0), 0, 64, arr)

    spool = SegmentSpool(
        str(tmp_path), 64, 1, wait_cb=late_ingest, poll_s=0.01,
        timeout_s=5.0,
    )
    segs = spool.acquire(0, 0)
    np.testing.assert_array_equal(segs[0], arr)
    assert spool.waits == 1
    with pytest.raises(RuntimeError, match="stalled"):
        SegmentSpool(str(tmp_path), 64, 3, poll_s=0.01,
                     timeout_s=0.05).acquire(2, 2)


# heavy GSPMD compiles on the 8-device virtual mesh: slow-marked so the
# tier-1 budget stays seed-level; the multichip-fleet CI job runs these
@pytest.mark.slow
def test_run_pipelined_end_to_end_with_workers(tmp_path):
    """The full stage composition in miniature: pool ingest workers ->
    SegmentSpool -> supervised PipelineStreamEngine, bit-exact vs a
    plain supervised stream run, segments persisted for resume."""
    from primesim_tpu.ingest.pipeline import run_pipelined, segment_path
    from primesim_tpu.ingest.stream import StreamEngine

    cfg_path = os.path.join(REPO, "configs", "rung1_64core_fft.json")
    with open(cfg_path) as f:
        cfg = MachineConfig.from_json(f.read())
    spec = "fft_like:n_phases=2,points_per_core=8"
    tr = synth.fft_like(64, n_phases=2, points_per_core=8)
    pool_dir = str(tmp_path / "pool")
    eng, sup, stats = run_pipelined(
        cfg, tr,
        synth_spec=spec,
        window_events=64,
        seg_events=128,
        ingest_workers=2,
        pool_dir=pool_dir,
        supervisor_kwargs={"snapshot_dir": str(tmp_path / "ckpt"),
                           "checkpoint_every_chunks": 4},
    )
    assert stats["pool"]["units_done"] == stats["segments"]
    assert os.path.exists(segment_path(pool_dir, 0))
    plain = StreamEngine(cfg, tr, window_events=64)
    plain.warmup()
    plain.run()
    np.testing.assert_array_equal(eng.cycles, plain.cycles)
    for k, v in plain.counters.items():
        np.testing.assert_array_equal(eng.counters[k], v, err_msg=k)
    assert sup.committed > 0


def test_ingest_units_join_the_lease_ledger_identity():
    from primesim_tpu.pool.units import build_ingest_units, build_units

    cfg = _cfg(8, n_banks=4)
    units = build_ingest_units(cfg, None, "fft_like", 128, 3)
    assert [u["unit_id"] for u in units] == ["g00000", "g00001", "g00002"]
    assert len({u["key"] for u in units}) == 3  # seg_index joins the key
    # sim units without a mesh keep their pre-pod key shape: devices
    # joins the identity only when set
    a = build_units(cfg, [], ["fft_like"], [{}], fold=False,
                    chunk_steps=64, max_steps=1000)
    b = build_units(cfg, [], ["fft_like"], [{}], fold=False,
                    chunk_steps=64, max_steps=1000, devices=4)
    assert a[0]["key"] != b[0]["key"]
    assert "devices" not in a[0]


# ---- rung-5 smoke slice (slow) --------------------------------------------


@pytest.mark.slow
def test_rung5_pipelined_sharded_smoke(tmp_path):
    """A thin slice of the acceptance run: the rung-5 wafer config,
    sharded over the 8-device virtual mesh, pipelined ingest, supervised
    with checkpoints — completing end-to-end on a short synthetic
    workload."""
    from primesim_tpu.ingest.pipeline import run_pipelined

    with open(os.path.join(
        REPO, "configs", "rung5_16384core_wafer.json"
    )) as f:
        cfg = MachineConfig.from_json(f.read())
    tr = synth.fft_like(16384, n_phases=1, points_per_core=2)
    eng, sup, stats = run_pipelined(
        cfg, tr,
        synth_spec="fft_like:n_phases=1,points_per_core=2",
        window_events=32,
        ingest_workers=2,
        pool_dir=str(tmp_path / "pool"),
        mesh=tile_mesh(8),
        supervisor_kwargs={"snapshot_dir": str(tmp_path / "ckpt"),
                           "checkpoint_every_chunks": 2},
    )
    assert stats["pool"]["units_done"] == stats["segments"]
    assert sup.committed > 0
    assert int(eng.counters["instructions"].sum()) > 0
    assert bool(np.asarray(eng.done))
