"""Machine zoo + calibration subsystem (DESIGN.md §25, ISSUE 19).

Covers the pluggable-topology contract (vectorized `path_links` vs the
memoized scalar `route_links` walk, link-for-link, on every topology),
MOESI dirty-sharing semantics and its divergence from MESI, the stride
prefetcher's counters, three-way golden/XLA/Pallas parity across zoo
selector combinations, link faults on torus/ring solo-vs-fleet, the
typed ConfigError/CalibError exit-2 contract, checkpoint round-trips of
the prefetcher state (format v7), and the `primetpu calibrate` fit
recovering synthetic ground-truth knobs.
"""

import dataclasses
import json

import numpy as np
import pytest

from primesim_tpu.calib.fit import (
    FIT_KEYS_DEFAULT,
    apply_fit,
    fit,
    knob_start,
    simulate_matrix,
    synthesize_observed,
)
from primesim_tpu.calib.table import (
    CalibEntry,
    CalibError,
    CalibTable,
    parse_table,
)
from primesim_tpu.config.machine import (
    FAULT_LINK_DEGRADE,
    FAULT_LINK_FAIL,
    ConfigError,
    FaultConfigError,
    MachineConfig,
    NocConfig,
    small_test_config,
)
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.noc import topology as topo
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_INS, EV_LD, EV_ST, from_event_lists

from test_parity import assert_parity


def zoo_cfg(n_cores=8, mesh_x=4, mesh_y=2, topology="mesh", **kw):
    noc = NocConfig(
        mesh_x=mesh_x, mesh_y=mesh_y, link_lat=1, router_lat=2,
        topology=topology,
    )
    kw.setdefault("n_banks", 4)
    kw.setdefault("quantum", 400)
    return small_test_config(n_cores, noc=noc, **kw)


# ---- topology plugins: scalar reference vs vectorized routes --------------


@pytest.mark.parametrize("topology", ["mesh", "torus", "ring"])
@pytest.mark.parametrize("mx,my", [(4, 4), (5, 3)])
def test_path_links_matches_route_links_all_pairs(topology, mx, my):
    cfg = zoo_cfg(n_cores=mx * my, mesh_x=mx, mesh_y=my, topology=topology)
    tiles = np.arange(cfg.n_tiles, dtype=np.int32)
    a = np.repeat(tiles, cfg.n_tiles)
    b = np.tile(tiles, cfg.n_tiles)
    paths = np.asarray(topo.path_links(cfg, a, b))
    assert paths.shape == (a.size, topo.path_width(cfg))
    hops = np.asarray(topo.hops(cfg, a, b, xp=np))
    for i in range(a.size):
        ref = topo.route_links(cfg, int(a[i]), int(b[i]))
        row = tuple(int(x) for x in paths[i] if x >= 0)
        assert row == ref, (topology, int(a[i]), int(b[i]))
        # hop count is the route length; routes fit the padded width
        assert hops[i] == len(ref)
        assert len(ref) <= topo.path_width(cfg)
    # hops are symmetric and zero only on the diagonal
    h = hops.reshape(cfg.n_tiles, cfg.n_tiles)
    np.testing.assert_array_equal(h, h.T)
    assert (np.diag(h) == 0).all() and (h + np.eye(cfg.n_tiles) > 0).all()


def test_torus_wraps_and_ring_stays_on_spine():
    t = zoo_cfg(n_cores=16, mesh_x=4, mesh_y=4, topology="torus")
    # opposite edge tiles are one wrap hop apart on the torus, not mx-1
    assert int(topo.hops(t, 0, 3, xp=np)) == 1
    assert int(topo.hops(dataclasses.replace(
        t, noc=dataclasses.replace(t.noc, topology="mesh")), 0, 3,
        xp=np)) == 3
    r = zoo_cfg(n_cores=16, mesh_x=4, mesh_y=4, topology="ring")
    # every vertical (N/S) link a ring route uses sits on the column-0
    # spine; cross-row routes pay row -> spine -> row
    for a in range(r.n_tiles):
        for b in range(r.n_tiles):
            for l in topo.route_links(r, a, b):
                if l % 4 in (2, 3):
                    assert (l // 4) % 4 == 0, (a, b, l)


def test_detour_hops_tables_per_topology():
    mesh = zoo_cfg(n_cores=16, mesh_x=4, mesh_y=4, topology="mesh")
    torus = zoo_cfg(n_cores=16, mesh_x=4, mesh_y=4, topology="torus")
    ring = zoo_cfg(n_cores=15, mesh_x=5, mesh_y=3, topology="ring")
    assert (topo.detour_hops_table(mesh) == 2).all()
    assert (topo.detour_hops_table(torus) == 2).all()
    tbl = topo.detour_hops_table(ring).reshape(-1, 4)
    assert tbl.shape[0] == ring.n_tiles
    assert (tbl[:, 0:2] == 5 - 2).all()  # row-ring detour: mx - 2
    assert (tbl[:, 2:4] == 3 - 2).all()  # spine detour: my - 2


# ---- MOESI: derived Owned state semantics ---------------------------------


def _two_core_sharing_trace():
    # core 0 dirties a line; core 1 reads it later (the INS batch orders
    # the arbitration); cores 2/3 idle
    return from_event_lists([
        [(EV_ST, 4, 0)],
        [(EV_INS, 50, 0), (EV_LD, 4, 0)],
        [],
        [],
    ])


def test_moesi_owner_retained_on_gets():
    tr = _two_core_sharing_trace()
    g = GoldenSim(small_test_config(4, coherence="moesi"), tr)
    g.run()
    # the GETS probed the dirty owner but left it in place: the home
    # still names core 0 owner, with both cores recorded as sharers
    assert int(g.counters["probes"][1]) == 1
    b, bs = g._bank(0), g._bank_set(0)
    w = next(w for w in range(g.cfg.llc.ways) if g.llc_tag[b, bs, w] == 0)
    assert int(g.llc_owner[b, bs, w]) == 0
    sharers = g._sharers_from(g.sharers, b, bs, w)
    assert set(sharers) == {0, 1}
    # derived O: core 0's stored M line is effectively Owned; core 1's is
    # a plain shared copy
    assert g._derived_owned(0, 0)
    assert not g._derived_owned(1, 0)


def test_mesi_demotes_owner_on_gets():
    tr = _two_core_sharing_trace()
    g = GoldenSim(small_test_config(4, coherence="mesi"), tr)
    g.run()
    b, bs = g._bank(0), g._bank_set(0)
    w = next(w for w in range(g.cfg.llc.ways) if g.llc_tag[b, bs, w] == 0)
    assert int(g.llc_owner[b, bs, w]) == -1  # written back + demoted
    assert not g._derived_owned(0, 0)


def test_moesi_diverges_from_mesi_on_shared_readers():
    # many readers of one dirty line: MOESI keeps probing the retained
    # owner, MESI demotes it once — the protocols must NOT be aliases
    tr = synth.uniform_random(8, n_mem_ops=96, shared_frac=0.8, seed=11)
    out = {}
    for proto in ("mesi", "moesi"):
        g = GoldenSim(small_test_config(8, coherence=proto), tr)
        g.run()
        out[proto] = (int(g.counters["probes"].sum()),
                      int(g.cycles.sum()))
    assert out["moesi"][0] > out["mesi"][0]
    assert out["moesi"] != out["mesi"]


# ---- stride prefetcher ----------------------------------------------------


def test_stride_prefetcher_covers_stream_misses():
    tr = synth.stream(4, n_mem_ops=96, seed=3)
    base = GoldenSim(small_test_config(4), tr)
    base.run()
    pf = GoldenSim(
        small_test_config(4, prefetcher="stride", prefetch_degree=4,
                          prefetch_lat=2),
        tr,
    )
    pf.run()
    assert int(base.counters["prefetch_hits"].sum()) == 0
    assert int(pf.counters["prefetch_hits"].sum()) > 0
    # a covered miss still fetched the line (dram_accesses counts it) —
    # it just paid the buffer latency instead of dram_lat
    np.testing.assert_array_equal(
        pf.counters["dram_accesses"], base.counters["dram_accesses"]
    )
    np.testing.assert_array_equal(
        pf.counters["instructions"], base.counters["instructions"]
    )
    assert int(pf.cycles.max()) < int(base.cycles.max())


def test_random_trace_trains_no_strides():
    tr = synth.uniform_random(4, n_mem_ops=64, shared_frac=0.0, seed=9)
    g = GoldenSim(
        small_test_config(4, prefetcher="stride", prefetch_degree=2,
                          prefetch_lat=2),
        tr,
    )
    g.run()
    # irregular addresses may fluke an occasional stride, but coverage
    # must be marginal, and the selector must not perturb retirement
    assert int(g.counters["prefetch_hits"].sum()) <= int(
        g.counters["dram_accesses"].sum()) // 4
    base = GoldenSim(small_test_config(4), tr)
    base.run()
    np.testing.assert_array_equal(
        g.counters["instructions"], base.counters["instructions"]
    )


# ---- typed config/table error contract ------------------------------------


@pytest.mark.parametrize(
    "kw,selector",
    [
        (dict(noc=NocConfig(2, 2, 1, 1, topology="taurus")), "noc_topology"),
        (dict(coherence="mosi"), "coherence"),
        (dict(coherence="moesi", sharer_group=2), "coherence"),
        (dict(prefetcher="ghb"), "prefetcher"),
        (dict(prefetcher="stride", prefetch_degree=0), "prefetch_degree"),
        (dict(prefetch_lat=-1), "prefetch_lat"),
    ],
)
def test_config_error_carries_selector_location(kw, selector):
    with pytest.raises(ConfigError) as ei:
        small_test_config(8, **kw)
    assert ei.value.location()["selector"] == selector


def test_ring_link_faults_need_rings_of_three():
    noc = NocConfig(2, 2, 1, 1, topology="ring")
    with pytest.raises(FaultConfigError, match="mesh_x >= 3"):
        small_test_config(
            4, noc=noc, faults_enabled=True, max_fault_events=1,
            fault_events=((1, FAULT_LINK_FAIL, 0, 0),),
        )
    # the same schedule is legal once the rings have a long way around
    cfg = zoo_cfg(
        n_cores=9, mesh_x=3, mesh_y=3, topology="ring",
        faults_enabled=True, max_fault_events=1,
        fault_events=((1, FAULT_LINK_FAIL, 0, 0),),
    )
    assert cfg.noc.topology == "ring"


@pytest.mark.parametrize(
    "mutate,entry,field",
    [
        (lambda t: t["entries"][0].update(generator="nope"), "e0",
         "generator"),
        (lambda t: t["entries"][0].update(metric="mips"), "e0", "metric"),
        (lambda t: t["entries"][0].update(observed=0), "e0", "observed"),
        (lambda t: t["entries"][0]["params"].update(n_mem_ops=1.5), "e0",
         "params"),
        (lambda t: t["entries"].append(dict(t["entries"][0])), "e0", None),
        (lambda t: t.pop("name"), None, "name"),
        (lambda t: t.update(entries=[]), None, "entries"),
    ],
)
def test_calib_table_validation(mutate, entry, field):
    t = {
        "name": "tbl",
        "entries": [{
            "name": "e0", "generator": "stream",
            "params": {"n_mem_ops": 32}, "metric": "total_cycles",
            "observed": 10.0,
        }],
    }
    mutate(t)
    with pytest.raises(CalibError) as ei:
        parse_table(json.dumps(t))
    loc = ei.value.location()
    if entry is not None:
        assert loc.get("entry") == entry
    if field is not None:
        assert loc.get("field") == field


def test_calibrate_cli_typed_errors_exit_2(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg_path = tmp_path / "m.json"
    cfg_path.write_text(small_test_config(4).to_json())
    bad = tmp_path / "bad.json"
    bad.write_text('{"name": "t", "entries": [{"name": "x"}]}')
    rc = main(["calibrate", str(cfg_path), "--table", str(bad)])
    assert rc == 2
    err = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
    assert err["error"]["type"] == "CalibError"
    assert err["error"]["location"]["entry"] == "x"

    good = tmp_path / "tbl.json"
    good.write_text(json.dumps({
        "name": "t",
        "entries": [{"name": "x", "generator": "stream",
                     "params": {"n_mem_ops": 32},
                     "metric": "total_cycles", "observed": 10.0}],
    }))
    rc = main(["calibrate", str(cfg_path), "--table", str(good),
               "--fit", "warp_speed"])
    assert rc == 2
    err = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
    assert err["error"]["type"] == "CalibError"
    assert err["error"]["location"]["field"] == "fit"


def test_cli_zoo_config_error_exit_2(tmp_path, capsys):
    from primesim_tpu.cli import main

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"n_cores": 4, "coherence": "dragon"}))
    rc = main(["info", str(p)])
    assert rc == 2
    err = json.loads(capsys.readouterr().err.strip().splitlines()[-1])
    assert err["error"]["type"] == "ConfigError"
    assert err["error"]["location"] == {
        "selector": "coherence", "value": "dragon",
    }


def test_config_comment_keys_are_annotations():
    d = json.loads(small_test_config(4).to_json())
    d["_comment"] = "machine-zoo configs ship provenance notes"
    assert MachineConfig.from_dict(d) == small_test_config(4)


# ---- lint: static selectors must not reach traced selects -----------------


def test_lint_flags_selector_inside_traced_select(tmp_path):
    from primesim_tpu.analysis.lint import run_lint

    def lint(relpath, src):
        p = tmp_path / relpath
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        res = run_lint(
            paths=[str(tmp_path)], root=str(tmp_path),
            baseline_path=str(tmp_path / "absent_baseline.json"),
            select=["PT-TRACED-BRANCH"],
        )
        p.unlink()
        return res

    bad = lint(
        "pkg/sim/bad.py",
        "import jax.numpy as jnp\n"
        "def f(cfg, a, b):\n"
        "    return jnp.where(cfg.coherence == 'moesi', a, b)\n",
    )
    assert [f.rule for f in bad.findings] == ["PT-TRACED-BRANCH"]
    assert "coherence" in bad.findings[0].message
    good = lint(
        "pkg/sim/good.py",
        "import jax.numpy as jnp\n"
        "def f(cfg, a, b):\n"
        "    if cfg.coherence == 'moesi':\n"
        "        return a\n"
        "    return jnp.where(a > b, a, b)\n",
    )
    assert good.findings == []


# ---- fleet knob plumbing --------------------------------------------------


def test_prefetch_knobs_are_fleet_overrides():
    from primesim_tpu.sim.fleet import KNOB_KEYS, apply_overrides

    cfg = small_test_config(4, prefetcher="stride")
    out = apply_overrides(cfg, {"prefetch_degree": 2, "prefetch_lat": 9})
    assert out == dataclasses.replace(
        cfg, prefetch_degree=2, prefetch_lat=9
    )
    # every fittable calibration knob is a fleet override key
    assert set(knob_start(cfg, FIT_KEYS_DEFAULT)) <= set(KNOB_KEYS)
    assert apply_fit(cfg, {"llc_lat": 7, "dram_lat": 55}) == \
        dataclasses.replace(
            cfg, llc=dataclasses.replace(cfg.llc, latency=7), dram_lat=55
        )


# ---- three-way parity across the zoo (slow: engine compiles) --------------

ZOO_COMBOS = [
    ("torus", "mesi", "none", "uniform_random"),
    ("ring", "mesi", "none", "uniform_random"),
    ("mesh", "moesi", "none", "uniform_random"),
    ("torus", "moesi", "stride", "fft_like"),
    ("ring", "mesi", "stride", "stream"),
]


def _zoo_trace(kind):
    if kind == "uniform_random":
        return synth.uniform_random(8, n_mem_ops=96, shared_frac=0.5, seed=5)
    if kind == "fft_like":
        return synth.fft_like(8, n_phases=2, points_per_core=12, seed=7)
    return synth.stream(8, n_mem_ops=96, seed=3)


@pytest.mark.slow
@pytest.mark.parametrize("topology,coherence,prefetcher,gen", ZOO_COMBOS)
def test_golden_engine_parity_zoo(topology, coherence, prefetcher, gen):
    cfg = zoo_cfg(
        topology=topology, coherence=coherence, prefetcher=prefetcher,
        prefetch_degree=4, prefetch_lat=3,
    )
    assert_parity(cfg, _zoo_trace(gen), chunk_steps=32)


@pytest.mark.slow
def test_pallas_step_parity_zoo():
    # every zoo selector at once through the Pallas step kernel: the
    # interpreter-mode kernel must match the XLA path bit-for-bit
    from primesim_tpu.sim.engine import Engine

    cfg = zoo_cfg(
        topology="torus", coherence="moesi", prefetcher="stride",
        prefetch_degree=4, prefetch_lat=3,
    )
    tr = _zoo_trace("fft_like")
    xla = Engine(cfg, tr, chunk_steps=32)
    xla.run()
    pal = Engine(
        dataclasses.replace(cfg, step_impl="pallas"), tr, chunk_steps=32
    )
    pal.run()
    np.testing.assert_array_equal(pal.cycles, xla.cycles)
    for k, v in xla.counters.items():
        np.testing.assert_array_equal(pal.counters[k], v, err_msg=k)


# ---- faults on torus/ring (slow) ------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("topology", ["torus", "ring"])
def test_zoo_link_faults_solo_vs_fleet(topology):
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.sim.fleet import FleetEngine

    cfg = zoo_cfg(
        n_cores=16, mesh_x=4, mesh_y=4, topology=topology,
        faults_enabled=True, max_fault_events=2,
        fault_events=(
            (5, FAULT_LINK_FAIL, 0, 0),       # tile 0 east: busy first hop
            (8, FAULT_LINK_DEGRADE, 22, 7),
        ),
    )
    tr = synth.uniform_random(16, n_mem_ops=96, shared_frac=0.4, seed=13)
    solo = Engine(cfg, tr, chunk_steps=32)
    solo.run()
    assert int(solo.counters["noc_reroutes"].sum()) > 0
    fleet = FleetEngine(cfg, [tr, tr], [{}, {"dram_lat": 140}],
                        chunk_steps=32)
    fleet.run()
    np.testing.assert_array_equal(
        np.asarray(fleet.cycles)[0], solo.cycles,
        err_msg=f"{topology}: fleet[0] != solo",
    )
    for k, v in solo.counters.items():
        np.testing.assert_array_equal(
            np.asarray(fleet.counters[k])[0], v, err_msg=k
        )
    # the overridden element genuinely diverges (the knobs are traced)
    assert int(np.asarray(fleet.cycles)[1].sum()) != int(solo.cycles.sum())


# ---- checkpoint format v7: prefetcher state survives resume (slow) --------


@pytest.mark.slow
def test_checkpoint_roundtrip_restores_prefetcher_state(tmp_path):
    from primesim_tpu.sim.engine import Engine

    cfg = small_test_config(
        8, n_banks=4, quantum=200, coherence="moesi",
        prefetcher="stride", prefetch_degree=4, prefetch_lat=3,
    )
    tr = synth.stream(8, n_mem_ops=96, seed=3)
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()
    assert int(ref.counters["prefetch_hits"].sum()) > 0

    a = Engine(cfg, tr, chunk_steps=16)
    a.run_steps(24)
    assert not a.done()
    ckpt = str(tmp_path / "zoo.npz")
    a.save_checkpoint(ckpt)
    b = Engine(cfg, tr, chunk_steps=16)
    b.load_checkpoint(ckpt)
    # the mid-run prefetcher training state came back (v7 payload), so
    # the resumed run replays the same covered misses
    np.testing.assert_array_equal(
        np.asarray(b.state.pf_line), np.asarray(a.state.pf_line)
    )
    b.run()
    np.testing.assert_array_equal(b.cycles, ref.cycles)
    for k, v in ref.counters.items():
        np.testing.assert_array_equal(b.counters[k], v, err_msg=k)


# ---- calibrate: synthetic ground-truth recovery (slow) --------------------


def _calib_table():
    return CalibTable(
        name="selftest",
        entries=(
            CalibEntry("chase", "pointer_chase",
                       {"n_mem_ops": 48, "n_nodes": 16},
                       "cycles_per_mem_op", 1.0),
            CalibEntry("xchg", "uniform_random",
                       {"n_mem_ops": 48, "shared_frac": 1, "seed": 1},
                       "cycles_per_mem_op", 1.0),
        ),
    )


@pytest.mark.slow
def test_calibrate_recovers_synthetic_truth():
    cfg = small_test_config(8, n_banks=4, quantum=500)
    truth = {"llc_lat": 16, "dram_lat": 151}
    table = synthesize_observed(cfg, _calib_table(), truth, chunk_steps=64)
    res = fit(cfg, table, fit_keys=tuple(truth), chunk_steps=64)
    assert res.cost <= 1e-9, res.report()
    assert res.knobs == truth
    assert res.start == {"llc_lat": 10, "dram_lat": 100}
    assert res.batch == 5 * 2  # N_CANDIDATES x entries, constant per run
    # the fitted knobs round-trip into a loadable machine config
    out = apply_fit(cfg, res.knobs)
    assert out.llc.latency == 16 and out.dram_lat == 151
    assert MachineConfig.from_dict(json.loads(out.to_json())) == out


@pytest.mark.slow
def test_simulate_matrix_is_monotone_in_dram_lat():
    cfg = small_test_config(8, n_banks=4, quantum=500)
    rows = simulate_matrix(
        cfg, _calib_table(),
        [{"dram_lat": 50}, {"dram_lat": 100}, {"dram_lat": 200}],
        chunk_steps=64,
    )
    for e in range(2):
        col = [rows[k][e] for k in range(3)]
        assert col[0] < col[1] < col[2]
