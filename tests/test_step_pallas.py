"""Pallas step-kernel subsystem (kernels/ — ISSUE 4 tentpole): routing
the step's phase-1 probe/classification and phase-4 commit through the
fused VMEM kernels (`step_impl="pallas"`) must be BIT-EXACT — cycles,
every stat counter, and the full machine state — against both the golden
model and the XLA step, on every workload generator and machine mode.
Interpreter mode on CPU runs the identical kernel logic tier-1-gated;
compiled on TPU.
"""

import dataclasses

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    CacheConfig,
    MachineConfig,
    NocConfig,
    small_test_config,
)
from primesim_tpu.sim.engine import Engine
from primesim_tpu.trace import synth

from test_parity import assert_parity


def _pallas(cfg):
    return dataclasses.replace(cfg, step_impl="pallas")


def assert_xla_pallas_match(cfg_xla, trace, chunk_steps=16):
    """Direct xla-vs-pallas compare of EVERYTHING an engine run produces:
    final cycles plus every MachineState field (L1 planes, directory
    rows, NoC/DRAM queue state, sync tables, counters, step)."""
    ex = Engine(cfg_xla, trace, chunk_steps=chunk_steps)
    ex.run()
    ep = Engine(_pallas(cfg_xla), trace, chunk_steps=chunk_steps)
    ep.run()
    np.testing.assert_array_equal(ex.cycles, ep.cycles, err_msg="cycles")
    for f in ex.state._fields:
        if f == "knobs":
            continue  # inputs, identical by construction
        a, b = getattr(ex.state, f), getattr(ep.state, f)
        if hasattr(a, "_fields"):  # nested pytree (faults): leaf-wise
            for sub in a._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(a, sub)),
                    np.asarray(getattr(b, sub)),
                    err_msg=f"state field {f}.{sub}",
                )
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"state field {f}"
        )


GENERATOR_TRACES = {
    "uniform_random": lambda: synth.uniform_random(8, n_mem_ops=50, seed=42),
    "stream": lambda: synth.stream(8, n_mem_ops=50, seed=43),
    "pointer_chase": lambda: synth.pointer_chase(
        8, n_mem_ops=40, n_nodes=32, seed=44
    ),
    "false_sharing": lambda: synth.false_sharing(8, n_mem_ops=40, seed=45),
    "fft_like": lambda: synth.fft_like(
        8, n_phases=2, points_per_core=8, seed=46
    ),
    "readers_writer": lambda: synth.readers_writer(8, n_rounds=3, seed=47),
    "lock_contention": lambda: synth.lock_contention(8, n_critical=6, seed=48),
    "barrier_phases": lambda: synth.barrier_phases(8, n_phases=3, seed=49),
}


@pytest.mark.parametrize("gen", sorted(GENERATOR_TRACES))
def test_three_way_parity_every_generator(gen):
    # golden vs pallas engine (assert_parity) AND xla vs pallas full
    # state: together the three implementations agree bit-for-bit
    cfg = small_test_config(8, n_banks=4, quantum=300)
    tr = GENERATOR_TRACES[gen]()
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


@pytest.mark.slow
def test_parity_local_runs():
    # rl > 0: the kernels take the deferred run-patch masks (hm/wm/cm)
    # as extra inputs — probe applies them, commit writes them back
    cfg = small_test_config(8, n_banks=4, quantum=400, local_run_len=4)
    tr = synth.false_sharing(8, n_mem_ops=40, seed=9)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr)


@pytest.mark.slow
def test_parity_folded_trace():
    from primesim_tpu.trace.format import fold_ins

    cfg = small_test_config(8, n_banks=4, quantum=400, local_run_len=4)
    tr = fold_ins(synth.fft_like(8, n_phases=2, points_per_core=8, seed=50))
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr)


@pytest.mark.slow
def test_parity_coarse_directory():
    # sharer_group > 1: group-granular sharer words + the epoch planes'
    # validation guard, both inside the kernels
    cfg = small_test_config(8, n_banks=4, quantum=400, sharer_group=4)
    tr = synth.readers_writer(8, n_rounds=3, seed=10)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr)


@pytest.mark.slow
def test_parity_router_noc_and_dram_queue():
    # cross-step queue state (link_free / dram_free) composes with the
    # kernels: phases 1/4 are fused, phase 3's queueing stays XLA
    noc = NocConfig(
        mesh_x=2, mesh_y=2, link_lat=1, router_lat=1,
        contention=True, contention_model="router", contention_lat=2,
    )
    cfg = small_test_config(8, n_banks=4, quantum=400, noc=noc)
    assert_xla_pallas_match(cfg, synth.uniform_random(8, n_mem_ops=40, seed=11))
    cfg2 = small_test_config(
        8, n_banks=4, quantum=400, dram_queue=True, dram_service=8
    )
    assert_xla_pallas_match(
        cfg2, synth.uniform_random(8, n_mem_ops=40, seed=12)
    )


def test_parity_with_pallas_reduce_combined():
    # step_impl="pallas" already routes reductions through the kernel;
    # setting pallas_reduce=True too must be equivalent, not conflicting
    cfg = small_test_config(8, n_banks=4, quantum=400, pallas_reduce=True)
    tr = synth.false_sharing(8, n_mem_ops=40, seed=13)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)


def test_parity_64core_multiblock():
    # C=64 still runs as one [64, ...] block (core_block pads at 128
    # multiples only), but exercises multi-word sharer sets, a tiny LLC
    # with back-invalidations, and a 4x4 mesh
    cfg = MachineConfig(
        n_cores=64, n_banks=16,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=4, mesh_y=4),
        quantum=500,
    )
    tr = synth.readers_writer(64, n_rounds=2, block_lines=4, seed=14)
    assert_parity(_pallas(cfg), tr, chunk_steps=32)
    assert_xla_pallas_match(cfg, tr, chunk_steps=32)


@pytest.mark.slow
def test_fleet_vmapped_pallas_step():
    # the fleet vmaps the whole step: the kernels must batch correctly
    # (no pl.program_id — core ids are data), with per-element traced
    # knob overrides still compiling ONCE
    from primesim_tpu.sim.fleet import FleetEngine, apply_overrides

    cfg = small_test_config(8, n_banks=4, quantum=300, step_impl="pallas")
    traces = [
        synth.false_sharing(8, n_mem_ops=40, seed=21),
        synth.uniform_random(8, n_mem_ops=60, seed=22),
        synth.lock_contention(8, n_critical=6, seed=23),
    ]
    overrides = [
        {},
        {"llc_lat": 25, "dram_lat": 140, "l1_lat": 4},
        {"quantum": 150, "cpi": 2},
    ]
    fleet = FleetEngine(cfg, traces, overrides, chunk_steps=32)
    fleet.run()
    assert fleet.done()
    for i, (t, ov) in enumerate(zip(traces, overrides)):
        solo = Engine(apply_overrides(cfg, ov), t, chunk_steps=32)
        solo.run()
        np.testing.assert_array_equal(
            fleet.cycles[i], solo.cycles, err_msg=f"elem {i} cycles"
        )
        es = fleet.element_state(i)
        for f in es._fields:
            if f == "knobs":
                continue
            a, b = getattr(es, f), getattr(solo.state, f)
            if hasattr(a, "_fields"):  # nested pytree (faults)
                for sub in a._fields:
                    np.testing.assert_array_equal(
                        np.asarray(getattr(a, sub)),
                        np.asarray(getattr(b, sub)),
                        err_msg=f"elem {i} state field {f}.{sub}",
                    )
                continue
            np.testing.assert_array_equal(
                np.asarray(a),
                np.asarray(b),
                err_msg=f"elem {i} state field {f}",
            )


@pytest.mark.slow
def test_fleet_vmapped_pallas_coarse():
    # coarse directory under the vmapped kernels (sharer_group is part
    # of the geometry key, shared by every element)
    from primesim_tpu.sim.fleet import FleetEngine

    cfg = small_test_config(
        8, n_banks=4, quantum=300, sharer_group=4, step_impl="pallas"
    )
    traces = [
        synth.readers_writer(8, n_rounds=3, seed=24),
        synth.false_sharing(8, n_mem_ops=40, seed=25),
    ]
    fleet = FleetEngine(cfg, traces, chunk_steps=32)
    fleet.run()
    assert fleet.done()
    for i, t in enumerate(traces):
        solo = Engine(cfg, t, chunk_steps=32)
        solo.run()
        np.testing.assert_array_equal(
            fleet.cycles[i], solo.cycles, err_msg=f"elem {i} cycles"
        )


def test_step_impl_validation_and_default():
    assert small_test_config(4).step_impl == "xla"  # default untouched
    with pytest.raises(ValueError, match="step_impl"):
        small_test_config(4, step_impl="mosaic")
