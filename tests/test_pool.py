"""Tests for the elastic worker pool (pool/): unit keys, the ledger
fold, the lease state machine (grant / heartbeat-renew / expiry /
redispatch / poison / hedge / first-ACK-wins), coordinator restart
recovery, and the worker's crash-resume bit-exactness.

Determinism discipline: coordinator tests drive a FAKE clock (the
`clock` constructor hook), so lease expiry happens exactly when the test
says — never because a slow CI box stalled a heartbeat. Worker threads
heartbeat on real time against that frozen clock, which renews deadlines
to the same instant and therefore never expires anything by accident.

The subprocess acceptance tests (real SIGKILL of a worker, real SIGKILL
of the coordinator mid-campaign) are @slow: tier-1 pins the protocol
in-process; the CI pool-chaos job runs the real-process wiring.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.pool import PoolCoordinator, PoolWorker, SimulatedCrash
from primesim_tpu.pool.units import (
    DONE,
    LEASED,
    PENDING,
    POISON,
    build_units,
    fold_unit_records,
    unit_key,
)
from primesim_tpu.serve.protocol import request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMALL_SYNTH = "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed={}"
#: several chunks at chunk_steps=8 — room to crash at chunk 2 and resume
CRASH_SYNTH = "fft_like:n_phases=2,points_per_core=16,ins_per_mem=4,seed={}"


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _cfg():
    return small_test_config(4)


def _units(n=2, synth=SMALL_SYNTH, chunk_steps=16):
    cfg = _cfg()
    synths = [synth.format(i) for i in range(n)]
    return cfg, build_units(
        cfg, [], synths, [{} for _ in range(n)],
        fold=True, chunk_steps=chunk_steps, max_steps=100_000,
    )


def _coord(tmp_path, units, name="pool", **kw):
    kw.setdefault("lease_ttl_s", 5.0)
    return PoolCoordinator(units, str(tmp_path / name), **kw)


def _lease(coord, worker):
    return coord.handle({"verb": "lease", "worker": worker})


def _ack(coord, worker, grant, result=None):
    u = grant["unit"]
    return coord.handle({
        "verb": "ack", "worker": worker, "unit_id": u["unit_id"],
        "epoch": grant["epoch"], "key": u["key"],
        "result": result or {"metric": "x", "value": 1},
        "resumed_steps": 0,
    })


def _reference_detail(cfg, unit):
    """The deterministic fields of a unit's result, computed in-process
    the same way `primetpu sweep` (no --workers) would."""
    from primesim_tpu.serve.scheduler import parse_synth_spec
    from primesim_tpu.sim.fleet import FleetEngine
    from primesim_tpu.sim.supervisor import RunSupervisor

    trace = parse_synth_spec(unit["synth"], cfg.n_cores, unit["fold"])
    fleet = FleetEngine(cfg, [trace], [{}],
                        chunk_steps=int(unit["chunk_steps"]))
    RunSupervisor(fleet, handle_signals=False).run(
        max_steps=int(unit["max_steps"]))
    ec = fleet.element_counters(0)
    return {
        "instructions": int(ec["instructions"].sum()),
        "max_core_cycles": int(fleet.cycles[0].max()),
        "noc_msgs": int(ec["noc_msgs"].sum()),
    }


# ---- unit identity -------------------------------------------------------


def test_unit_key_stable_and_workload_sensitive():
    cfg, units = _units(2)
    _, again = _units(2)
    assert [u["key"] for u in units] == [u["key"] for u in again]
    assert units[0]["key"] != units[1]["key"]  # different synth seed
    # any workload-identity field moves the key...
    bumped = dict(units[0], chunk_steps=units[0]["chunk_steps"] * 2)
    assert unit_key(bumped) != units[0]["key"]
    # ...but warm_cache is an execution HINT, not identity (forking from
    # a proven prefix is bit-exact, so the result is the same result)
    hinted = dict(units[0], warm_cache=True)
    assert unit_key(hinted) == units[0]["key"]


def test_build_units_pairing_mismatch_raises():
    cfg = _cfg()
    with pytest.raises(ValueError, match="fan rule"):
        build_units(cfg, [], [SMALL_SYNTH.format(0)], [{}, {}],
                    fold=True, chunk_steps=16, max_steps=100)


# ---- ledger fold ---------------------------------------------------------


def test_fold_first_ack_wins_under_duplicates_and_reorder():
    lease = {"t": "lease", "unit_id": "u0", "worker": "w0", "epoch": 1,
             "key": "k", "hedge": False}
    ack1 = {"t": "ack", "unit_id": "u0", "worker": "w1", "epoch": 2,
            "key": "k", "result": {"v": "first"}, "resumed_steps": 7}
    ack2 = {"t": "ack", "unit_id": "u0", "worker": "w0", "epoch": 1,
            "key": "k", "result": {"v": "late"}, "resumed_steps": 0}
    # ack arriving BEFORE its lease record is still authoritative, the
    # second ack (hedge loser / redelivery) is discarded whatever its
    # epoch claims
    units, clean = fold_unit_records([ack1, lease, ack2])
    assert units["u0"]["result"] == {"v": "first"}
    assert units["u0"]["result_epoch"] == 2
    assert units["u0"]["resumed_steps"] == 7
    assert units["u0"]["max_epoch"] == 2
    assert not clean
    # order-independent: any interleaving keeps the first ack in stream
    units2, _ = fold_unit_records([lease, ack1, ack1, ack2, ack2])
    assert units2["u0"]["result"] == {"v": "first"}


def test_fold_expire_accumulates_distinct_workers_across_restarts():
    recs = [
        {"t": "expire", "unit_id": "u0", "worker": "w0", "epoch": 1},
        {"t": "expire", "unit_id": "u0", "worker": "w0", "epoch": 2},
        {"t": "expire", "unit_id": "u0", "worker": "w1", "epoch": 3},
    ]
    units, _ = fold_unit_records(recs)
    assert units["u0"]["kills"] == {"w0", "w1"}  # distinct, not 3
    assert units["u0"]["max_epoch"] == 3
    # an expire landing AFTER the ack doesn't un-finish the unit
    ack = {"t": "ack", "unit_id": "u0", "worker": "w2", "epoch": 4,
           "key": "k", "result": {"v": 1}, "resumed_steps": 0}
    units2, _ = fold_unit_records([ack] + recs)
    assert units2["u0"]["result"] == {"v": 1}


def test_fold_poison_sticks_unless_a_result_exists():
    poison = {"t": "poison", "unit_id": "u0", "key": "k",
              "kills": ["w0", "w1"]}
    units, _ = fold_unit_records([poison])
    assert units["u0"]["poison"] and units["u0"]["kills"] == {"w0", "w1"}
    # a hedged twin's result beats the poison verdict — keep the data
    ack = {"t": "ack", "unit_id": "u0", "worker": "w2", "epoch": 3,
           "key": "k", "result": {"v": 1}, "resumed_steps": 0}
    units2, _ = fold_unit_records([ack, poison])
    assert units2["u0"]["result"] == {"v": 1}
    assert not units2["u0"]["poison"]


def test_fold_drain_marker_only_counts_when_last():
    drain = {"t": "drain"}
    lease = {"t": "lease", "unit_id": "u0", "worker": "w0", "epoch": 1,
             "key": "k", "hedge": False}
    assert fold_unit_records([lease, drain])[1] is True
    assert fold_unit_records([drain, lease])[1] is False


# ---- lease state machine (fake clock, direct handle()) -------------------


def test_lease_heartbeat_renew_expire_redispatch_epochs(tmp_path):
    clk = FakeClock()
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False, clock=clk)
    try:
        g = _lease(coord, "w0")
        assert g["ok"] and g["epoch"] == 1 and g["checkpoint"] is None
        assert g["unit"]["unit_id"] == "u00000"
        assert g["lease_ttl_s"] == 5.0

        # heartbeat renews: 4s + 4s straddles the original 5s deadline
        clk.advance(4.0)
        hb = coord.handle({"verb": "heartbeat", "worker": "w0",
                           "unit_id": "u00000", "epoch": 1, "steps": 32})
        assert hb["ok"] and not hb.get("lost")
        clk.advance(4.0)
        coord.tick()
        assert coord.stats()["units"][LEASED] == 1  # renewed, still held

        # silence past the TTL: expire -> kill evidence -> PENDING
        clk.advance(6.0)
        coord.tick()
        s = coord.stats()
        assert s["units"][PENDING] == 1
        assert s["counters"]["expired"] == 1

        # re-dispatch bumps the epoch and counts as a redispatch
        g2 = _lease(coord, "w1")
        assert g2["epoch"] == 2
        assert coord.stats()["counters"]["redispatches"] == 1

        # the presumed-dead worker's heartbeat is now stale: lost
        hb2 = coord.handle({"verb": "heartbeat", "worker": "w0",
                            "unit_id": "u00000", "epoch": 1})
        assert hb2["lost"]
        # ...and its old-epoch ack is still ACCEPTED (first-ACK-wins:
        # the unit is deterministic, a slow worker's result counts)
        a = _ack(coord, "w0", g)
        assert a["accepted"]
        assert coord.stats()["units"][DONE] == 1
        assert _lease(coord, "w1").get("done")
    finally:
        coord.close()


def test_idle_reply_when_everything_is_leased(tmp_path):
    clk = FakeClock()
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False, clock=clk)
    try:
        assert _lease(coord, "w0")["ok"]
        r = _lease(coord, "w1")
        assert r.get("idle") and r["retry_after_s"] == 1.0  # ttl/5
        hb = coord.handle({"verb": "heartbeat", "worker": "w1",
                           "unit_id": "nope", "epoch": 1})
        assert hb["lost"]  # unknown unit
    finally:
        coord.close()


def test_poison_needs_distinct_workers(tmp_path):
    clk = FakeClock()
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False, clock=clk,
                   poison_threshold=2)
    try:
        # the SAME worker dying twice is one distinct killer: no poison
        for _ in range(2):
            assert _lease(coord, "w0")["ok"]
            clk.advance(6.0)
            coord.tick()
        assert coord.stats()["units"][PENDING] == 1

        # a second distinct killer crosses the threshold: quarantine
        assert _lease(coord, "w1")["ok"]
        clk.advance(6.0)
        coord.tick()
        s = coord.stats()
        assert s["units"][POISON] == 1
        assert s["counters"]["poisoned"] == 1
        assert coord.done  # the campaign proceeds without the unit
        assert _lease(coord, "w2").get("done")
        r = coord.results()[0]
        assert r["state"] == POISON and r["kills"] == ["w0", "w1"]
    finally:
        coord.close()


def test_hedge_grants_twin_and_first_ack_wins(tmp_path):
    clk = FakeClock()
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=True, clock=clk)
    try:
        g0 = _lease(coord, "w0")
        g1 = _lease(coord, "w1")  # PENDING dry, w0 in flight: hedge twin
        assert g1["hedge"] and g1["unit"]["unit_id"] == "u00000"
        assert g1["epoch"] == 2 and coord.stats()["counters"]["hedges"] == 1
        # one twin at a time — a third worker idles
        assert _lease(coord, "w2").get("idle")

        a1 = _ack(coord, "w1", g1, result={"v": "winner"})
        assert a1["accepted"]
        a0 = _ack(coord, "w0", g0, result={"v": "loser"})
        assert a0["duplicate"] and not a0["accepted"]
        s = coord.stats()
        assert s["counters"]["acks"] == 1 and s["counters"]["duplicates"] == 1
        assert coord.results()[0]["result"] == {"v": "winner"}
    finally:
        coord.close()


def test_ack_key_mismatch_is_rejected(tmp_path):
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False)
    try:
        g = _lease(coord, "w0")
        bad = coord.handle({
            "verb": "ack", "worker": "w0", "unit_id": "u00000",
            "epoch": g["epoch"], "key": "deadbeefdeadbeef",
            "result": {}, "resumed_steps": 0,
        })
        assert not bad["ok"] and "key mismatch" in bad["error"]["detail"]
        assert coord.stats()["units"][LEASED] == 1  # nothing accepted
    finally:
        coord.close()


# ---- restart recovery ----------------------------------------------------


def test_restart_replays_ledger_and_readopts_inflight_lease(tmp_path):
    clk = FakeClock()
    cfg, units = _units(2)
    pool_dir = str(tmp_path / "pool")
    c1 = PoolCoordinator(units, pool_dir, hedge=False, clock=clk)
    g0 = _lease(c1, "w0")
    assert _ack(c1, "w0", g0, result={"v": "kept"})["accepted"]
    g1 = _lease(c1, "w1")  # in flight at "crash"
    assert g1["unit"]["unit_id"] == "u00001"
    c1.close()  # no drain: simulates kill -9 (the ledger IS the state)

    _, units_again = _units(2)
    c2 = PoolCoordinator(units_again, pool_dir, hedge=False, clock=clk)
    try:
        assert c2.recovered["results_adopted"] == 1
        assert c2.recovered["stale_entries"] == 0
        assert not c2.recovered["clean_drain"]
        s = c2.stats()
        assert s["units"][DONE] == 1 and s["units"][PENDING] == 1
        assert c2.results()[0]["result"] == {"v": "kept"}

        # the worker that outlived the coordinator heartbeats its current
        # epoch: the lease is RE-ADOPTED instead of re-dispatched
        hb = c2.handle({"verb": "heartbeat", "worker": "w1",
                        "unit_id": "u00001", "epoch": g1["epoch"]})
        assert hb["ok"] and not hb.get("lost")
        assert c2.stats()["units"][LEASED] == 1
        assert _ack(c2, "w1", g1)["accepted"]
        assert c2.done
    finally:
        c2.close()


def test_restart_rejects_ledger_of_a_changed_campaign(tmp_path):
    cfg, units = _units(1)
    pool_dir = str(tmp_path / "pool")
    c1 = PoolCoordinator(units, pool_dir, hedge=False)
    assert _ack(c1, "w0", _lease(c1, "w0"))["accepted"]
    c1.close()

    # same unit ids, different workload: the journaled result must NOT
    # be inherited by a campaign it doesn't describe
    _, changed = _units(1, synth=CRASH_SYNTH)
    c2 = PoolCoordinator(changed, pool_dir, hedge=False)
    try:
        assert c2.recovered["results_adopted"] == 0
        assert c2.recovered["stale_entries"] >= 1
        assert c2.stats()["units"][PENDING] == 1
    finally:
        c2.close()


# ---- socket front door ---------------------------------------------------


def test_socket_roundtrip_lease_status_metrics(tmp_path):
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False)
    coord.start()
    try:
        sock = coord.socket_path
        g = request(sock, {"verb": "lease", "worker": "w0"})
        assert g["ok"] and g["unit"]["unit_id"] == "u00000"
        st = request(sock, {"verb": "status"})
        assert st["units"][LEASED] == 1 and st["workers_seen"] == ["w0"]
        m = request(sock, {"verb": "metrics"})
        assert 'primetpu_pool_units{state="LEASED"} 1' in m["text"]
        assert "primetpu_pool_leases_total 1" in m["text"]
        bad = request(sock, {"verb": "frobnicate"})
        assert not bad["ok"] and "unknown verb" in bad["error"]["detail"]
    finally:
        coord.close()


# ---- worker execution ----------------------------------------------------


def test_worker_campaign_bit_exact_vs_inprocess(tmp_path):
    """One worker drains a 2-unit campaign over the real socket; every
    deterministic result field matches the in-process sweep path."""
    cfg, units = _units(2)
    coord = _coord(tmp_path, units, lease_ttl_s=30.0)
    coord.start()
    try:
        w = PoolWorker(coord.socket_path, "w0", reconnect_timeout_s=10.0)
        assert w.run() == 0
        assert w.units_done == 2 and coord.done
        for u, r in zip(units, coord.results()):
            assert r["state"] == DONE
            d = r["result"]["detail"]
            assert r["result"]["metric"] == "simulated_MIPS"
            ref = _reference_detail(cfg, u)
            for k, v in ref.items():
                assert d[k] == v, (u["unit_id"], k)
            assert d["fleet_index"] == u["index"]
        # results are durable; unit checkpoints are gone (dead weight)
        assert os.listdir(os.path.join(coord.pool_dir, "units")) == []
    finally:
        coord.close()


def test_worker_crash_redispatch_resumes_checkpoint_bit_exact(tmp_path):
    """The acceptance property in miniature: worker A dies (simulated
    SIGKILL) after 2 committed chunks; the lease expires; worker B
    re-leases the unit, resumes from A's element checkpoint (not step 0),
    and the final result is bit-exact vs an uncrashed run."""
    clk = FakeClock()
    cfg, units = _units(1, synth=CRASH_SYNTH, chunk_steps=8)
    coord = _coord(tmp_path, units, hedge=False, clock=clk)
    coord.start()
    try:
        wa = PoolWorker(coord.socket_path, "wA", reconnect_timeout_s=10.0,
                        crash_after_chunks=2, simulate_crash=True)
        g = request(coord.socket_path, {"verb": "lease", "worker": "wA"})
        with pytest.raises(SimulatedCrash):
            wa.run_unit(g)
        ckpt = os.path.join(coord.pool_dir, "units", "u00000.npz")
        assert os.path.exists(ckpt)  # chunk 2 committed before the kill

        clk.advance(6.0)  # heartbeats stopped with wA: lease expires
        coord.tick()
        s = coord.stats()
        assert s["counters"]["expired"] >= 1
        assert s["units"][PENDING] == 1

        wb = PoolWorker(coord.socket_path, "wB", reconnect_timeout_s=10.0)
        assert wb.run() == 0
        r = coord.results()[0]
        assert r["state"] == DONE
        assert r["resumed_steps"] > 0  # resumed mid-flight, not step 0
        assert r["kills"] == ["wA"]
        assert coord.stats()["counters"]["redispatches"] == 1
        ref = _reference_detail(cfg, units[0])
        for k, v in ref.items():
            assert r["result"]["detail"][k] == v, k
        assert not os.path.exists(ckpt)  # reaped on ack
    finally:
        coord.close()


def test_worker_acks_quarantined_result_for_bad_unit(tmp_path):
    """A unit that can't even materialize must not kill the worker: it
    acks a structured quarantined result and the campaign moves on."""
    cfg, units = _units(1)
    units[0]["synth"] = "no_such_kernel:oops=1"
    units[0]["key"] = unit_key(units[0])
    coord = _coord(tmp_path, units)
    coord.start()
    try:
        w = PoolWorker(coord.socket_path, "w0", reconnect_timeout_s=10.0)
        assert w.run() == 0
        r = coord.results()[0]
        assert r["state"] == DONE
        assert r["result"]["metric"] == "quarantined"
        assert r["result"]["detail"]["status"] == "quarantined"
        assert r["result"]["detail"]["error"]["type"]
    finally:
        coord.close()


# ---- observability -------------------------------------------------------


def test_pool_events_reach_trace_and_report_section(tmp_path):
    import numpy as np

    from primesim_tpu.obs import Recorder
    from primesim_tpu.stats.counters import COUNTER_NAMES
    from primesim_tpu.stats.report import render_report

    clk = FakeClock()
    rec = Recorder("full")
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, hedge=False, clock=clk, obs=rec)
    try:
        g = _lease(coord, "w0")
        clk.advance(6.0)
        coord.tick()  # expire
        g2 = _lease(coord, "w1")  # redispatch
        _ack(coord, "w1", g2)
        kinds = {e["name"] for e in rec.trace.events if e["ph"] == "i"}
        assert {"lease", "expire", "redispatch", "ack"} <= kinds

        counters = {k: np.zeros(4, dtype=np.int64) for k in COUNTER_NAMES}
        text = render_report(cfg, counters, np.zeros(4, dtype=np.int64),
                             pool=coord.pool_report())
        lines = text.splitlines()
        assert "POOL" in lines

        def row(label):
            return next(l for l in lines if l.startswith(f"  {label}"))

        assert row("units done").endswith(" 1")
        assert row("expired leases").endswith(" 1")
        assert row("redispatches").endswith(" 1")
        assert row("units poisoned").endswith(" 0")
    finally:
        coord.close()


# ---- subprocess acceptance (real processes, real SIGKILL) ----------------


def _write_cfg(tmp_path):
    p = str(tmp_path / "cfg.json")
    with open(p, "w") as f:
        f.write(_cfg().to_json())
    return p


def _sweep_cmd(cfg_path, synths, extra=()):
    cmd = [sys.executable, "-m", "primesim_tpu.cli", "sweep", cfg_path,
           "--chunk-steps", "16"]
    for s in synths:
        cmd += ["--synth", s]
    return cmd + list(extra)


def _parse_elements(out):
    rows = [json.loads(ln) for ln in out.splitlines() if ln.strip()]
    elems = [r for r in rows if r["metric"] == "simulated_MIPS"]
    for r in elems:  # wall-clock fields legitimately differ
        r.pop("value")
        r["detail"].pop("wall_s")
    return sorted(elems, key=lambda r: r["detail"]["fleet_index"])


@pytest.mark.slow
def test_subprocess_worker_kill9_campaign_bit_exact(tmp_path):
    """Chaos acceptance: one of three workers SIGKILLs itself mid-unit
    (the crash hook the CI pool-chaos job uses); the campaign completes
    with per-element JSON identical to the single-process sweep, and the
    pool report shows the recovery actually happened."""
    cfg_path = _write_cfg(tmp_path)
    synths = [SMALL_SYNTH.format(i) for i in range(4)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    base = subprocess.run(
        _sweep_cmd(cfg_path, synths), cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420,
    )
    assert base.returncode == 0, base.stderr[-2000:]

    chaos = subprocess.run(
        _sweep_cmd(cfg_path, synths, extra=(
            "--workers", "3", "--lease-ttl", "2.0", "--hedge", "off",
            "--pool-dir", str(tmp_path / "pool"),
        )),
        cwd=REPO, env={**env, "PRIMETPU_POOL_CRASH": "w0:2"},
        capture_output=True, text=True, timeout=420,
    )
    assert chaos.returncode == 0, chaos.stderr[-2000:]

    assert _parse_elements(chaos.stdout) == _parse_elements(base.stdout)
    agg = [json.loads(ln) for ln in chaos.stdout.splitlines()
           if '"fleet_aggregate_MIPS"' in ln]
    pool = agg[0]["detail"]["pool"]
    assert pool["units_done"] == 4 and pool["units_poisoned"] == 0
    # w0's suicide must be visible as expiry -> redispatch (hedging is
    # off, so nothing rescues the unit early)
    assert pool["expired_leases"] >= 1
    assert pool["redispatches"] >= 1


@pytest.mark.slow
def test_subprocess_coordinator_kill9_restart_resumes(tmp_path):
    """Durability acceptance: SIGKILL the whole campaign (coordinator +
    workers share a process group), rerun the identical command with the
    same --pool-dir, and the restart must replay the ledger and resume
    interrupted units from their checkpoints — committed chunks are
    never re-simulated (visible as resumed_steps > 0 in the ack)."""
    cfg_path = _write_cfg(tmp_path)
    pool_dir = str(tmp_path / "pool")
    slow = "fft_like:n_phases=8,points_per_core=256,ins_per_mem=4,seed={}"
    cmd = _sweep_cmd(cfg_path, [slow.format(1), slow.format(2)], extra=(
        "--workers", "1", "--lease-ttl", "3.0", "--pool-dir", pool_dir,
    ))
    cmd[cmd.index("--chunk-steps") + 1] = "8"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    try:
        deadline = time.monotonic() + 240
        units_dir = os.path.join(pool_dir, "units")
        while time.monotonic() < deadline:
            if os.path.isdir(units_dir) and os.listdir(units_dir):
                break
            assert proc.poll() is None, "campaign finished before the kill"
            time.sleep(0.5)
        else:
            pytest.fail("no unit checkpoint appeared before the kill")
        time.sleep(3.0)  # let a few more chunks commit
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)

    redo = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=540)
    assert redo.returncode == 0, redo.stderr[-2000:]
    assert len(_parse_elements(redo.stdout)) == 2

    from primesim_tpu.serve.journal import JobJournal

    records, _ = JobJournal(pool_dir).replay()
    folded, _ = fold_unit_records(records)
    assert any(u["result"] is not None and u["resumed_steps"] > 0
               for u in folded.values()), "nothing resumed mid-flight"
