"""FleetEngine correctness (ISSUE 2 tentpole).

The contract is crisp: fleet element i must be BIT-EXACT with a solo
`Engine` run of the same effective (config, trace) — final cycles, every
stat counter, and the full machine state (L1/LLC/directory arrays, sync
tables, LRU stamps, even the step counter: the batched while_loop
select-masks finished elements at exactly the chunk boundary where a solo
run_loop with the same chunk_steps stops). And a whole parameter sweep
must be ONE compilation: the static jit key is the timing-normalized
geometry, with every timing knob traced.
"""

import numpy as np
import pytest

from primesim_tpu.analysis.recompile import recompile_sentinel
from primesim_tpu.config.machine import small_test_config
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.fleet import (
    FleetEngine,
    apply_overrides,
    fleet_run_loop,
)
from primesim_tpu.trace import synth


def assert_element_matches_solo(fleet, i, cfg_eff, trace, chunk_steps):
    solo = Engine(cfg_eff, trace, chunk_steps=chunk_steps)
    solo.run()
    np.testing.assert_array_equal(
        fleet.cycles[i], solo.cycles, err_msg=f"elem {i} cycles"
    )
    fc = fleet.element_counters(i)
    for k, v in solo.counters.items():
        np.testing.assert_array_equal(
            fc[k], v, err_msg=f"elem {i} counter {k}"
        )
    es = fleet.element_state(i)
    for f in es._fields:
        if f == "knobs":
            continue  # knobs are inputs, compared via cfg_eff already
        a, b = getattr(es, f), getattr(solo.state, f)
        if hasattr(a, "_fields"):  # nested pytree (faults): leaf-wise
            for sub in a._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(a, sub)),
                    np.asarray(getattr(b, sub)),
                    err_msg=f"elem {i} state field {f}.{sub}",
                )
            continue
        np.testing.assert_array_equal(
            np.asarray(a),
            np.asarray(b),
            err_msg=f"elem {i} state field {f}",
        )


def test_fleet_parity_mixed_traces_and_knobs():
    # the acceptance bar: B=4 elements, ALL with distinct traces AND
    # distinct traced timing knobs, one of them a sync (lock) workload
    cfg = small_test_config(8, n_banks=4, quantum=300)
    traces = [
        synth.false_sharing(8, n_mem_ops=40, seed=11),
        synth.uniform_random(8, n_mem_ops=60, seed=12),
        synth.lock_contention(8, n_critical=6, seed=13),
        synth.barrier_phases(8, n_phases=3, seed=14),
    ]
    overrides = [
        {},
        {"llc_lat": 25, "dram_lat": 140, "l1_lat": 4},
        {"quantum": 150, "cpi": 2},
        {"link_lat": 3, "router_lat": 2, "cpi": [1, 2, 1, 2, 3, 1, 1, 2]},
    ]
    # the whole 4-element knob sweep must be ONE compilation of the
    # fleet program (jit key = timing-normalized geometry)
    with recompile_sentinel(allowed=1, watch=("fleet",),
                            label="mixed traces+knobs sweep"):
        fleet = FleetEngine(cfg, traces, overrides, chunk_steps=32)
        fleet.run()
    assert fleet.done() and list(fleet.done_mask()) == [True] * 4
    for i, (t, ov) in enumerate(zip(traces, overrides)):
        assert_element_matches_solo(
            fleet, i, apply_overrides(cfg, ov), t, chunk_steps=32
        )


def test_fleet_parity_contention_and_dram_queue_knobs():
    # traced knobs that feed the queueing models: contention_lat (tile
    # model) and dram_service/dram_lat (memory-controller queue)
    cfg = small_test_config(
        8,
        n_banks=4,
        dram_queue=True,
        dram_service=20,
    )
    import dataclasses

    cfg = dataclasses.replace(
        cfg,
        noc=dataclasses.replace(cfg.noc, contention=True,
                                contention_model="tile"),
    )
    traces = [
        synth.false_sharing(8, n_mem_ops=40, seed=21),
        synth.uniform_random(8, n_mem_ops=50, seed=22),
        synth.fft_like(8, n_phases=2, points_per_core=12, seed=23),
    ]
    overrides = [
        {},
        {"contention_lat": 7, "dram_service": 35},
        {"dram_service": 0, "dram_lat": 90, "contention_lat": 2},
    ]
    with recompile_sentinel(allowed=1, watch=("fleet",),
                            label="contention/dram knob sweep"):
        fleet = FleetEngine(cfg, traces, overrides, chunk_steps=32)
        fleet.run()
    for i, (t, ov) in enumerate(zip(traces, overrides)):
        assert_element_matches_solo(
            fleet, i, apply_overrides(cfg, ov), t, chunk_steps=32
        )


@pytest.mark.slow
def test_fleet_parity_router_model():
    # the router NoC model's link_free clocks rebase per element with a
    # per-element quantum — the hairiest drain/rebase interaction
    import dataclasses

    cfg = small_test_config(8, n_banks=4, quantum=400)
    cfg = dataclasses.replace(
        cfg,
        noc=dataclasses.replace(
            cfg.noc, contention=True, contention_model="router"
        ),
    )
    traces = [
        synth.false_sharing(8, n_mem_ops=40, seed=31),
        synth.uniform_random(8, n_mem_ops=50, seed=32),
        synth.false_sharing(8, n_mem_ops=40, seed=33),
    ]
    overrides = [{}, {"link_lat": 4, "quantum": 250}, {"router_lat": 5}]
    with recompile_sentinel(allowed=1, watch=("fleet",),
                            label="router-model knob sweep"):
        fleet = FleetEngine(cfg, traces, overrides, chunk_steps=16)
        fleet.run()
    for i, (t, ov) in enumerate(zip(traces, overrides)):
        assert_element_matches_solo(
            fleet, i, apply_overrides(cfg, ov), t, chunk_steps=16
        )


@pytest.mark.slow
def test_fleet_one_compilation_per_geometry():
    # changing only TRACED timing knobs between fleet runs must not
    # retrigger compilation; changing geometry must
    cfg = small_test_config(8, n_banks=4)
    traces = [synth.uniform_random(8, n_mem_ops=30, seed=41)]
    f1 = FleetEngine(cfg, traces, [{"llc_lat": 12}], chunk_steps=16)
    f1.run()
    n0 = fleet_run_loop._cache_size()
    f2 = FleetEngine(
        cfg, traces, [{"llc_lat": 33, "quantum": 500, "cpi": 3}],
        chunk_steps=16,
    )
    f2.run()
    assert fleet_run_loop._cache_size() == n0, (
        "knob-only change recompiled the fleet loop"
    )
    # sanity: the two runs really simulated different machines
    assert int(f1.cycles.max()) != int(f2.cycles.max())
    cfg_geo = small_test_config(4, n_banks=4)
    f3 = FleetEngine(
        cfg_geo, [synth.uniform_random(4, n_mem_ops=30, seed=42)],
        chunk_steps=16,
    )
    f3.run()
    assert fleet_run_loop._cache_size() == n0 + 1  # new geometry compiles


def test_fleet_rejections():
    cfg = small_test_config(4, n_banks=4)
    tr = synth.stream(4, n_mem_ops=10, seed=51)
    with pytest.raises(ValueError, match="at least one trace"):
        FleetEngine(cfg, [])
    with pytest.raises(ValueError, match="must match 1:1"):
        FleetEngine(cfg, [tr], [{}, {}])
    with pytest.raises(ValueError, match="unknown timing override"):
        FleetEngine(cfg, [tr], [{"llc_latency": 3}])
    with pytest.raises(ValueError, match="pallas"):
        FleetEngine(
            small_test_config(4, n_banks=4, pallas_reduce=True), [tr]
        )
    with pytest.raises(ValueError, match="quantum"):
        apply_overrides(cfg, {"quantum": 2**30})


@pytest.mark.slow
def test_fleet_uneven_lengths_and_early_finish():
    # elements finishing chunks apart: the short element must freeze
    # bit-exactly while the long one keeps the fleet's while_loop live
    cfg = small_test_config(4, n_banks=4)
    traces = [
        synth.stream(4, n_mem_ops=4, seed=61),
        synth.uniform_random(4, n_mem_ops=120, seed=62),
        synth.stream(4, n_mem_ops=40, seed=63),
    ]
    fleet = FleetEngine(cfg, traces, chunk_steps=8)
    fleet.run()
    for i, t in enumerate(traces):
        assert_element_matches_solo(fleet, i, cfg, t, chunk_steps=8)


def test_cli_sweep(tmp_path, capsys):
    import json

    from primesim_tpu.cli import main
    from primesim_tpu.config.machine import MachineConfig

    cfg = MachineConfig(n_cores=8, n_banks=8)
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(cfg.to_json())
    rep_dir = str(tmp_path / "reports")
    rc = main(
        [
            "sweep", cfg_path,
            "--synth", "false_sharing:n_mem_ops=30",
            "--vary", "llc_lat=10",
            "--vary", "llc_lat=40,dram_lat=200",
            "--vary", "quantum=500",
            "--chunk-steps", "32",
            "--report-dir", rep_dir,
        ]
    )
    assert rc == 0
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
    ]
    assert len(lines) == 4  # 3 elements + aggregate
    assert [d["detail"]["fleet_index"] for d in lines[:3]] == [0, 1, 2]
    assert lines[3]["metric"] == "fleet_aggregate_MIPS"
    assert lines[3]["detail"]["instructions"] == sum(
        d["detail"]["instructions"] for d in lines[:3]
    )
    # element 1's slower LLC/DRAM must cost cycles vs element 0
    assert (
        lines[1]["detail"]["max_core_cycles"]
        > lines[0]["detail"]["max_core_cycles"]
    )
    # one report per element, golden machine line reflects the override
    import os

    rep1 = open(os.path.join(rep_dir, "element_1.txt")).read()
    assert "fleet element 1" in rep1 and "lat 40" in rep1

    # each element must equal a solo CLI run of the same effective config
    from primesim_tpu.sim.fleet import apply_overrides as ao

    solo_cfg = ao(cfg, {"llc_lat": 40, "dram_lat": 200})
    solo_path = str(tmp_path / "solo.json")
    with open(solo_path, "w") as f:
        f.write(solo_cfg.to_json())
    rc = main(
        ["run", solo_path, "--synth", "false_sharing:n_mem_ops=30"]
    )
    assert rc == 0
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert (
        d["detail"]["max_core_cycles"]
        == lines[1]["detail"]["max_core_cycles"]
    )
    assert d["detail"]["instructions"] == lines[1]["detail"]["instructions"]
