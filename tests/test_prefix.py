"""Prefix forking + on-disk warm-state cache (ISSUE 9, DESIGN.md §16).

The contract: a sweep whose elements share (trace, timing knobs, ECC
rates) and differ only in inputs that cannot influence the machine
before the fault-schedule start pays for that shared prefix ONCE — a
solo Engine runs it, the snapshot broadcasts into the fleet slots via
`FleetEngine.fork_element`, and the forked campaign is BIT-EXACT with
the unforked one (cycles, every counter, the full machine state
including L1/directory arrays). A second identical campaign against a
warm cache skips the prefix simulation entirely; a corrupt or tampered
cache entry falls back to recompute; and a supervisor kill→resume of a
forked run stays bit-exact (the checkpoint carries fork provenance).
"""

import dataclasses
import json
import os
import signal

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    FAULT_LINK_DEGRADE,
    MachineConfig,
    small_test_config,
)
from primesim_tpu.sim.checkpoint import (
    CheckpointCorrupt,
    find_warm_states,
    load_warm_state,
    trace_fingerprint,
    warm_key,
)
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.fleet import FleetEngine, apply_overrides
from primesim_tpu.sim.prefix import (
    NEVER,
    dedup_plan,
    execute_prefix_plan,
    group_divergence,
    plan_prefix,
)
from primesim_tpu.sim.supervisor import Preempted, RunSupervisor
from primesim_tpu.trace import synth

EV_STEP = 40  # fault-schedule start: the divergence point of a seed sweep
CHUNK = 16
PREFIX = EV_STEP // CHUNK * CHUNK  # chunk-floored fork point (32)


def _chaos_cfg(**kw):
    cfg = small_test_config(8, n_banks=4, quantum=200, **kw)
    return dataclasses.replace(
        cfg,
        faults_enabled=True,
        max_fault_events=1,
        fault_events=((EV_STEP, FAULT_LINK_DEGRADE, 0, 3),),
    )


def _trace(seed=41):
    return synth.fft_like(8, n_phases=2, points_per_core=12, seed=seed)


def _seed_fleet(cfg, n=16, trace=None):
    tr = trace if trace is not None else _trace()
    ovs = [{"fault_seed": 100 + i} for i in range(n)]
    return FleetEngine(cfg, [tr] * n, ovs, chunk_steps=CHUNK)


def _assert_fleets_equal(a, b):
    np.testing.assert_array_equal(a.cycles, b.cycles)
    np.testing.assert_array_equal(a.steps_run, b.steps_run)
    for k, v in a.counters.items():
        np.testing.assert_array_equal(v, b.counters[k], err_msg=k)
    for f in a.state._fields:
        va, vb = getattr(a.state, f), getattr(b.state, f)
        if hasattr(va, "_fields"):  # nested pytree (faults): leaf-wise
            for sub in va._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(va, sub)),
                    np.asarray(getattr(vb, sub)),
                    err_msg=f"state field {f}.{sub}",
                )
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=f"state field {f}"
        )


# ---- divergence analysis ---------------------------------------------------


def test_chaos_seed_sweep_forks_at_schedule_start():
    fleet = _seed_fleet(_chaos_cfg(), n=4)
    groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
    assert len(groups) == 1
    g = groups[0]
    assert g.indices == [0, 1, 2, 3]
    assert g.divergence == EV_STEP  # the fault-schedule start
    assert g.prefix_steps == PREFIX  # chunk-floored below it


def test_plan_classes_split_on_trace_knobs_and_live_seed():
    cfg = _chaos_cfg()
    base = _trace(41)
    other = _trace(99)
    traces = [base, base, other, other, base, base]
    ovs = [
        {"fault_seed": 1},
        {"fault_seed": 2},
        {"fault_seed": 3},
        {"fault_seed": 4},
        # knob overrides diverge at step 0: never grouped with the rest
        {"fault_seed": 5, "dram_lat": 200},
        {"fault_seed": 6, "llc_lat": 20},
    ]
    fleet = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
    assert [g.indices for g in groups] == [[0, 1], [2, 3]]

    # nonzero flip rates make the seed live from step 0: seed-varying
    # elements become singleton classes and nothing is forked
    ecc = dataclasses.replace(cfg, fault_flip_l1=0.25)
    fleet = _seed_fleet(ecc, n=4)
    assert plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK) == []

    # mode off plans nothing; an integer mode caps the prefix
    fleet = _seed_fleet(cfg, n=4)
    assert plan_prefix(fleet.elem_cfgs, fleet.traces, mode="off") == []
    capped = plan_prefix(
        fleet.elem_cfgs, fleet.traces, mode="16", chunk_steps=CHUNK
    )
    assert capped[0].prefix_steps == 16


def test_group_divergence_rules():
    cfg = _chaos_cfg()
    # fully identical configs never diverge (dedup's domain, not forking's)
    assert group_divergence([cfg, cfg]) == NEVER
    # seed-varying, rates zero: the fault-schedule start
    a = dataclasses.replace(cfg, fault_seed=1)
    b = dataclasses.replace(cfg, fault_seed=2)
    assert group_divergence([a, b]) == EV_STEP
    # schedules differing in a later event diverge at the non-common one
    c = dataclasses.replace(
        cfg,
        max_fault_events=2,
        fault_events=cfg.fault_events + ((77, FAULT_LINK_DEGRADE, 1, 2),),
    )
    assert group_divergence([cfg, c]) == 77


def test_dedup_plan_detects_identical_elements():
    cfg = _chaos_cfg()
    tr = _trace()
    cfgs = [
        apply_overrides(cfg, {"fault_seed": 1}),
        apply_overrides(cfg, {"fault_seed": 1}),
        apply_overrides(cfg, {"fault_seed": 2}),
    ]
    keep, dup_of = dedup_plan(cfgs, [tr, tr, tr])
    assert keep == [0, 2] and dup_of == {1: 0}
    # a different trace with the same config is NOT a duplicate
    keep, dup_of = dedup_plan(cfgs[:2], [tr, _trace(99)])
    assert keep == [0, 1] and dup_of == {}


# ---- fork-from-snapshot bit-exactness --------------------------------------


def test_forked_seed_sweep_bit_exact_vs_unforked():
    cfg = _chaos_cfg()
    ref = _seed_fleet(cfg)
    ref.run()
    # the schedule must fire mid-run or the fixture proves nothing
    assert int(ref.steps_run.max()) > EV_STEP

    fleet = _seed_fleet(cfg)
    groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
    assert len(groups) == 1 and groups[0].indices == list(range(16))
    st = execute_prefix_plan(fleet, groups)
    assert st["forked_elements"] == 16
    assert st["prefix_steps"] == PREFIX
    assert list(fleet.prefix_steps) == [PREFIX] * 16
    assert list(fleet.steps_run) == [PREFIX] * 16
    fleet.run()
    _assert_fleets_equal(fleet, ref)

    # and against a solo Engine of one element's effective config:
    # counters, cycles, and the L1/directory state arrays all match
    solo = Engine(fleet.elem_cfgs[3], fleet.traces[3], chunk_steps=CHUNK)
    solo.run()
    np.testing.assert_array_equal(fleet.cycles[3], solo.cycles)
    fc = fleet.element_counters(3)
    for k, v in solo.counters.items():
        np.testing.assert_array_equal(fc[k], v, err_msg=k)
    es = fleet.element_state(3)
    for f in ("l1", "dirm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(es, f)),
            np.asarray(getattr(solo.state, f)),
            err_msg=f,
        )


@pytest.mark.slow
def test_forked_mixed_groups_and_singletons_bit_exact():
    # two prefix-sharing classes (different traces) plus a knob-override
    # singleton that is NOT forked — all coexisting in one fleet
    cfg = _chaos_cfg()
    traces = [_trace(41), _trace(41), _trace(99), _trace(99), _trace(41)]
    ovs = [
        {"fault_seed": 1},
        {"fault_seed": 2},
        {"fault_seed": 3},
        {"fault_seed": 4},
        {"fault_seed": 5, "dram_lat": 250},
    ]
    ref = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    ref.run()

    fleet = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
    assert [g.indices for g in groups] == [[0, 1], [2, 3]]
    st = execute_prefix_plan(fleet, groups)
    assert st["groups"] == 2 and st["forked_elements"] == 4
    assert list(fleet.prefix_steps) == [PREFIX, PREFIX, PREFIX, PREFIX, 0]
    fleet.run()
    _assert_fleets_equal(fleet, ref)


# ---- warm-state cache ------------------------------------------------------


def _forked_fleet(cfg, root, rec=None, n=4):
    fleet = _seed_fleet(cfg, n=n)
    groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
    st = execute_prefix_plan(
        fleet, groups, warm_cache=True, cache_root=root, obs=rec
    )
    return fleet, st


def test_warm_cache_hit_skips_prefix_simulation(tmp_path):
    from primesim_tpu.obs import Recorder

    cfg = _chaos_cfg()
    root = str(tmp_path / "warm")

    rec1 = Recorder("basic")
    fleet1, st1 = _forked_fleet(cfg, root, rec1)
    assert (st1["cache_hits"], st1["cache_misses"]) == (0, 1)
    # the miss path simulated the prefix: obs saw prefix-labeled chunks
    labels1 = rec1.store.summary()["labels"]
    assert labels1["prefix"]["chunks"] == PREFIX // CHUNK

    rec2 = Recorder("basic")
    fleet2, st2 = _forked_fleet(cfg, root, rec2)
    assert (st2["cache_hits"], st2["cache_misses"]) == (1, 0)
    assert st2["prefix_wall_s"] == 0.0
    # the hit path skipped the prefix ENTIRELY: zero prefix-labeled
    # chunks ever reached the recorder
    assert rec2.store.summary() is None

    fleet1.run()
    fleet2.run()
    _assert_fleets_equal(fleet1, fleet2)

    # the sidecar index finds the entry by config alone, deepest first
    found = find_warm_states(root, fleet1.elem_cfgs[0],
                             trace_fingerprint(fleet1.traces[0]))
    assert found and found[0][0] == PREFIX


def test_corrupt_cache_entry_falls_back_to_recompute(tmp_path):
    cfg = _chaos_cfg()
    root = str(tmp_path / "warm")
    _, st1 = _forked_fleet(cfg, root)
    assert st1["cache_misses"] == 1

    ref = _seed_fleet(cfg, n=4)
    ref.run()

    # tear every cached npz in half: load must fail closed, the planner
    # must recompute (and replace the entry), results must stay bit-exact
    npzs = [p for p in os.listdir(root) if p.endswith(".npz")]
    assert npzs
    for p in npzs:
        full = os.path.join(root, p)
        blob = open(full, "rb").read()
        with open(full, "wb") as f:
            f.write(blob[: len(blob) // 2])
    fleet2, st2 = _forked_fleet(cfg, root)
    assert (st2["cache_hits"], st2["cache_misses"]) == (0, 1)
    fleet2.run()
    _assert_fleets_equal(fleet2, ref)

    # the bad entry was overwritten: the next campaign hits
    _, st3 = _forked_fleet(cfg, root)
    assert st3["cache_hits"] == 1


def test_warm_key_sensitivity():
    cfg = _chaos_cfg()
    tr = _trace()
    fp = trace_fingerprint(tr)
    k0 = warm_key(cfg, fp, PREFIX)

    # trace change misses
    assert warm_key(cfg, trace_fingerprint(_trace(99)), PREFIX) != k0
    # geometry change misses (different LLC capacity)
    geo = dataclasses.replace(
        cfg, llc=dataclasses.replace(cfg.llc, size=cfg.llc.size * 2)
    )
    assert warm_key(geo, fp, PREFIX) != k0
    # knob change misses (traced, but part of the warm payload)
    assert warm_key(apply_overrides(cfg, {"dram_lat": 200}), fp, PREFIX) != k0
    # step-count change misses
    assert warm_key(cfg, fp, PREFIX + CHUNK) != k0
    # seed change with all ECC rates zero HITS: the seed is
    # architecturally unreachable before the schedule start
    assert warm_key(dataclasses.replace(cfg, fault_seed=7), fp, PREFIX) == k0
    # ... but with a nonzero flip rate the seed is live from step 0
    ecc = dataclasses.replace(cfg, fault_flip_l1=0.25)
    assert (
        warm_key(dataclasses.replace(ecc, fault_seed=7), fp, PREFIX)
        != warm_key(ecc, fp, PREFIX)
    )
    # events BELOW the prefix are pinned; an event at/after it is not
    late = dataclasses.replace(
        cfg, fault_events=((EV_STEP + 100, FAULT_LINK_DEGRADE, 0, 3),)
    )
    assert warm_key(late, fp, PREFIX) == warm_key(
        dataclasses.replace(cfg, fault_events=()), fp, PREFIX
    )


def test_load_warm_state_rejects_mismatched_key(tmp_path):
    cfg = _chaos_cfg()
    root = str(tmp_path / "warm")
    _forked_fleet(cfg, root)
    fp = trace_fingerprint(_trace())
    key = warm_key(cfg, fp, PREFIX)
    # asking for the entry under a different effective config must fail
    # closed (recomputed key mismatch), not silently serve wrong state
    other = apply_overrides(cfg, {"dram_lat": 200})
    with pytest.raises((CheckpointCorrupt, ValueError, FileNotFoundError)):
        load_warm_state(root, key, other, fp, PREFIX)
    # the honest request loads
    snap = load_warm_state(root, key, cfg, fp, PREFIX)
    assert int(snap["steps_run"]) == PREFIX


# ---- supervisor compose ----------------------------------------------------


def _kill_at(chunk):
    def on_chunk(sup):
        if sup.committed == chunk:
            os.kill(os.getpid(), signal.SIGTERM)

    return on_chunk


def test_supervisor_resume_of_forked_run_bit_exact(tmp_path):
    cfg = _chaos_cfg()

    def forked(n=4):
        fleet = _seed_fleet(cfg, n=n)
        groups = plan_prefix(fleet.elem_cfgs, fleet.traces, chunk_steps=CHUNK)
        execute_prefix_plan(fleet, groups)
        return fleet

    # uninterrupted supervised forked run = the reference cadence
    ref = forked()
    RunSupervisor(ref).run()

    eng = forked()
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path), checkpoint_every_chunks=1,
        on_chunk=_kill_at(2),
    )
    with pytest.raises(Preempted):
        sup.run()
    assert not eng.done()

    # resume into a FRESH, UNFORKED fleet: the snapshot alone must carry
    # everything (including the fork provenance, logged on resume)
    eng2 = _seed_fleet(cfg, n=4)
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path))
    assert sup2.resume() is not None
    assert any("resume-prefix" in ln for ln in sup2.log_lines())
    assert int(np.asarray(eng2.prefix_steps).max()) == PREFIX
    sup2.run()
    np.testing.assert_array_equal(eng2.cycles, ref.cycles)
    for k, v in eng2.counters.items():
        np.testing.assert_array_equal(v, ref.counters[k], err_msg=k)
    _assert_fleets_equal(eng2, ref)


# ---- CLI surface -----------------------------------------------------------


def _write_cfg(tmp_path):
    p = str(tmp_path / "m.json")
    with open(p, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    return p


def _write_schedule(tmp_path):
    p = str(tmp_path / "sched.json")
    with open(p, "w") as f:
        json.dump(
            {"events": [{"step": EV_STEP, "kind": "link_degrade",
                         "link": 0, "extra": 3}]},
            f,
        )
    return p


def _json_lines(capsys):
    cap = capsys.readouterr()
    return (
        [json.loads(ln) for ln in cap.out.splitlines() if ln.startswith("{")],
        cap.err,
    )


def _elem_lines(lines):
    out = []
    for d in lines:
        if d["metric"] != "simulated_MIPS":
            continue
        det = dict(d["detail"])
        det.pop("wall_s")
        out.append(det)
    return out


@pytest.mark.slow
def test_cli_sweep_fork_and_warm_cache(tmp_path, capsys, monkeypatch):
    from primesim_tpu.cli import main

    monkeypatch.setenv("PRIMETPU_CACHE_DIR", str(tmp_path / "cache"))
    cfg = _write_cfg(tmp_path)
    sched = _write_schedule(tmp_path)
    argv = [
        "sweep", cfg,
        "--synth", "fft_like:n_phases=2,points_per_core=12",
        "--fault-schedule", sched,
        "--vary", "fault_seed=0",
        "--vary", "fault_seed=1",
        "--vary", "fault_seed=2",
        "--vary", "fault_seed=3",
        "--chunk-steps", "16",
    ]
    # unforked reference
    assert main(argv) == 0
    ref_lines, _ = _json_lines(capsys)
    assert not any(d["metric"] == "prefix_fork" for d in ref_lines)

    # forked + warm cache, cold: one miss, parity with unforked
    assert main(argv + ["--fork-prefix", "auto", "--warm-cache", "on"]) == 0
    l1, _ = _json_lines(capsys)
    pf1 = [d for d in l1 if d["metric"] == "prefix_fork"][0]["detail"]
    assert pf1["forked_elements"] == 4
    assert (pf1["cache_hits"], pf1["cache_misses"]) == (0, 1)
    assert _elem_lines(l1) == _elem_lines(ref_lines)

    # second identical sweep: cache hit, NO prefix simulation, identical
    # per-element results
    assert main(argv + ["--fork-prefix", "auto", "--warm-cache", "on"]) == 0
    l2, _ = _json_lines(capsys)
    pf2 = [d for d in l2 if d["metric"] == "prefix_fork"][0]["detail"]
    assert (pf2["cache_hits"], pf2["cache_misses"]) == (1, 0)
    assert pf2["prefix_wall_s"] == 0.0
    assert _elem_lines(l2) == _elem_lines(ref_lines)


def test_cli_sweep_dedup_fans_out(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    rc = main(
        [
            "sweep", cfg,
            "--synth", "false_sharing:n_mem_ops=30",
            "--vary", "llc_lat=12",
            "--vary", "llc_lat=12",
            "--vary", "llc_lat=30",
            "--chunk-steps", "16",
        ]
    )
    assert rc == 0
    lines, err = _json_lines(capsys)
    assert "deduplicated 1 identical element(s)" in err
    elems = {d["detail"]["fleet_index"]: d["detail"]
             for d in lines if d["metric"] == "simulated_MIPS"}
    assert elems[1]["dedup_of"] == 0
    assert elems[1]["instructions"] == elems[0]["instructions"]
    assert elems[1]["max_core_cycles"] == elems[0]["max_core_cycles"]
    assert "dedup_of" not in elems[2]
    agg = [d for d in lines if d["metric"] == "fleet_aggregate_MIPS"][0]
    assert agg["detail"]["deduplicated"] == [1]
    # duplicates don't double-count the aggregate
    assert agg["detail"]["instructions"] == (
        elems[0]["instructions"] + elems[2]["instructions"]
    )


def test_cli_vary_errors_are_structured_exit_2(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    base = ["sweep", cfg, "--synth", "fft_like:n_phases=2"]

    # integer-parse failure lists the valid knob keys and exits 2 with
    # the structured {"error": ...} JSON (the typed-error contract)
    rc = main(base + ["--vary", "dram_lat=abc"])
    assert rc == 2
    _, err = _json_lines(capsys)
    line = [ln for ln in err.splitlines() if ln.startswith("{")][-1]
    obj = json.loads(line)["error"]
    assert obj["type"] == "VarySpecError"
    assert "fault_seed" in obj["detail"]  # the valid-keys listing
    assert obj["location"] == {"pair": "dram_lat=abc"}

    rc = main(base + ["--vary", "bogus=3"])
    assert rc == 2
    _, err = _json_lines(capsys)
    line = [ln for ln in err.splitlines() if ln.startswith("{")][-1]
    obj = json.loads(line)["error"]
    assert obj["type"] == "VarySpecError"
    assert "bogus" in obj["detail"] and "quantum" in obj["detail"]
