"""Router-occupancy NoC contention model (SURVEY.md §2 #6, BASELINE rung 3).

Hand-computed golden charges, golden-vs-engine bit-exact parity with the
model enabled (memory + sync paths), and the load-dependence property the
rung-3 "NoC-congestion heavy" config exists to show.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    CacheConfig,
    MachineConfig,
    NocConfig,
    small_test_config,
)
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, EV_LOCK, EV_UNLOCK, from_event_lists

from test_parity import assert_parity


def cfg4(contention=True, **kw):
    return small_test_config(
        4,
        noc=NocConfig(
            mesh_x=2, mesh_y=2, link_lat=1, router_lat=1,
            contention=contention, contention_lat=1,
        ),
        **kw,
    )


def test_golden_same_tile_transactions_queue():
    # lines 0 and 4 share home bank 0 (tile 0) but land in different
    # (bank,set) slots: both win the same step, count=2 at tile 0, each
    # charged +1. Cold LLC miss path: l1 + req + llc + dram + rep (+1).
    tr = from_event_lists([[(EV_LD, 4, 0)], [(EV_LD, 4, 4 * 64)], [], []])
    g = GoldenSim(cfg4(), tr)
    g.run()
    # c0 (tile 0 -> tile 0): 2+1+10+100+1 = 114 + 1 contention
    # c1 (tile 1 -> tile 0): 2+3+10+100+3 = 118 + 1 contention
    np.testing.assert_array_equal(g.cycles[:2], [115, 119])
    np.testing.assert_array_equal(g.counters["noc_contention_cycles"][:2], [1, 1])
    # same trace without contention: no extra
    g0 = GoldenSim(cfg4(contention=False), tr)
    g0.run()
    np.testing.assert_array_equal(g0.cycles[:2], [114, 118])


def test_golden_different_tiles_no_queue():
    # lines 0 (bank 0, tile 0) and 1 (bank 1, tile 1): disjoint home
    # tiles, no contention charge
    tr = from_event_lists([[(EV_LD, 4, 0)], [(EV_LD, 4, 64)], [], []])
    g = GoldenSim(cfg4(), tr)
    g.run()
    assert g.counters["noc_contention_cycles"].sum() == 0


def test_golden_lock_rmw_queues_with_memory():
    # core 0's LD and core 1's LOCK both target home tile 0 in the same
    # step: the lock RMW queues behind the memory transaction and vice
    # versa (+1 each)
    tr = from_event_lists(
        [[(EV_LD, 4, 0)], [(EV_LOCK, 0, 4 * 64), (EV_UNLOCK, 0, 4 * 64)], [], []]
    )
    g = GoldenSim(cfg4(), tr)
    g.run()
    assert g.counters["noc_contention_cycles"][0] == 1  # LD queued once
    # lock attempt queued once; unlock ran alone in the next step
    assert g.counters["noc_contention_cycles"][1] == 1


@pytest.mark.parametrize(
    "gen",
    ["false_sharing", "uniform_random", "lock_contention", "barrier_phases"],
)
def test_parity_with_contention(gen):
    cfg = cfg4(n_banks=4, quantum=300)
    tr = {
        "false_sharing": lambda: synth.false_sharing(4, n_mem_ops=40, seed=51),
        "uniform_random": lambda: synth.uniform_random(4, n_mem_ops=50, seed=52),
        "lock_contention": lambda: synth.lock_contention(4, n_critical=8, seed=53),
        "barrier_phases": lambda: synth.barrier_phases(4, n_phases=2, seed=54),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=50)


def test_parity_contention_8core_hot_bank():
    # every core hammers lines on ONE home bank: maximal router occupancy
    cfg = small_test_config(
        8, n_banks=4,
        noc=NocConfig(mesh_x=2, mesh_y=2, contention=True, contention_lat=3),
    )
    evs = [
        [(EV_LD, 4, (4 * i) * 64) for i in range(6)] for _ in range(8)
    ]  # lines 0,4,8,...: all bank 0
    assert_parity(cfg, from_event_lists(evs))


# -------------------------------------------------- per-link ("link") model


def test_engine_path_links_match_scalar_walk():
    # the vectorized XY path builder must be link-for-link identical to
    # the scalar noc.mesh.xy_links reference on every tile pair
    import numpy as np

    from primesim_tpu.noc.mesh import xy_links
    from primesim_tpu.sim.engine import _path_links
    import jax.numpy as jnp

    cfg = small_test_config(4, noc=NocConfig(mesh_x=4, mesh_y=3))
    nt = cfg.n_tiles
    a = np.repeat(np.arange(nt), nt).astype(np.int32)
    b = np.tile(np.arange(nt), nt).astype(np.int32)
    got = np.asarray(_path_links(cfg, jnp.asarray(a), jnp.asarray(b)))
    for k in range(nt * nt):
        want = xy_links(int(a[k]), int(b[k]), 4)
        row = tuple(x for x in got[k].tolist() if x >= 0)
        assert row == want, (int(a[k]), int(b[k]), row, want)


def test_golden_link_model_shared_link_queues():
    # 1x4 mesh (tiles 0-1-2-3 in a row). Core 0 (tile 0) -> bank 2
    # (tile 2) and core 1 (tile 1) -> bank 3 (tile 3): requests share the
    # eastward link out of tile 1 (and tile 2's), so BOTH transactions
    # queue (+1 each) even though their home TILES differ — exactly what
    # the tile model cannot see.
    cfg = small_test_config(
        4, n_banks=4,
        noc=NocConfig(mesh_x=4, mesh_y=1, contention=True,
                      contention_model="link", contention_lat=1),
    )
    tr = from_event_lists(
        [[(EV_LD, 4, 2 * 64)], [(EV_LD, 4, 3 * 64)], [], []]
    )
    g = GoldenSim(cfg, tr)
    g.run()
    np.testing.assert_array_equal(
        g.counters["noc_contention_cycles"][:2], [1, 1]
    )
    # same machine under the tile model: different home tiles, no charge
    cfg_t = small_test_config(
        4, n_banks=4,
        noc=NocConfig(mesh_x=4, mesh_y=1, contention=True,
                      contention_model="tile", contention_lat=1),
    )
    gt = GoldenSim(cfg_t, tr)
    gt.run()
    assert gt.counters["noc_contention_cycles"].sum() == 0


def test_golden_link_model_disjoint_paths_free():
    # 2x2 mesh: core 0 (tile 0) -> bank 1 (tile 1) east link; core 2
    # (tile 2) -> bank 3 (tile 3) east link at the other row — disjoint
    cfg = small_test_config(
        4, n_banks=4,
        noc=NocConfig(mesh_x=2, mesh_y=2, contention=True,
                      contention_model="link", contention_lat=1),
    )
    tr = from_event_lists(
        [[(EV_LD, 4, 1 * 64)], [], [(EV_LD, 4, 3 * 64)], []]
    )
    g = GoldenSim(cfg, tr)
    g.run()
    assert g.counters["noc_contention_cycles"].sum() == 0


@pytest.mark.parametrize(
    "gen", ["false_sharing", "lock_contention", "barrier_phases"]
)
@pytest.mark.slow
def test_parity_link_model(gen):
    cfg = small_test_config(
        8, n_banks=4, quantum=300,
        noc=NocConfig(mesh_x=4, mesh_y=2, contention=True,
                      contention_model="link", contention_lat=2),
    )
    tr = {
        "false_sharing": lambda: synth.false_sharing(8, n_mem_ops=40, seed=71),
        "lock_contention": lambda: synth.lock_contention(8, n_critical=8, seed=72),
        "barrier_phases": lambda: synth.barrier_phases(8, n_phases=2, seed=73),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=50)


@pytest.mark.slow
def test_parity_link_model_16core_hot_path():
    # many cores streaming through the same mesh column: heavy shared-link
    # occupancy, engine and golden must agree bit-exactly
    cfg = MachineConfig(
        n_cores=16, n_banks=16,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=4, mesh_y=4, contention=True,
                      contention_model="link", contention_lat=1),
        quantum=400,
    )
    evs = [
        [(EV_LD, 4, ((c + i) % 16) * 64) for i in range(8)] for c in range(16)
    ]
    assert_parity(cfg, from_event_lists(evs), chunk_steps=50)


def test_contention_is_load_dependent():
    # the rung-3 property: a hot-BANK workload (all cores stream distinct
    # sets of the same bank, staggered so several (bank,set) winners land
    # on one tile per step) takes longer — and reports queueing cycles —
    # with contention on than off. (Same-LINE traffic alone never queues:
    # the (bank,set) serializer admits one winner per slot per step.)
    evs = [
        [(EV_LD, 4, (4 * ((i + 2 * c) % 16)) * 64) for i in range(12)]
        for c in range(8)
    ]  # lines 0,4,8,...: all home bank 0, 16 distinct sets
    tr = from_event_lists(evs)
    on = GoldenSim(small_test_config(8, n_banks=4, noc=NocConfig(
        mesh_x=2, mesh_y=2, contention=True, contention_lat=2)), tr)
    on.run()
    off = GoldenSim(small_test_config(8, n_banks=4, noc=NocConfig(
        mesh_x=2, mesh_y=2, contention=False)), tr)
    off.run()
    assert on.counters["noc_contention_cycles"].sum() > 0
    assert on.cycles.max() > off.cycles.max()
