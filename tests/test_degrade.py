"""Degraded-mode elasticity (DESIGN.md §26): unified disk-pressure
governance + device-loss recovery.

The ENOSPC tests drive the `disk.preflight` chaos site — a plan event
opens a sustained window during which every free-space probe reports
zero bytes, so the evict -> compact -> backpressure ladder runs on a
healthy filesystem. Each governed write site (journal append, snapshot
rotation, exec/warm cache stores) must degrade without losing an ACKed
record or a committed chunk.

The device-loss tests drive the `devices.revoke` site against sharded
supervised runs; they need more than one visible device, so the
mesh-shrinking assertions skip on a 1-device backend and run for real
in CI under `XLA_FLAGS=--xla_force_host_platform_device_count=8` (the
degrade-chaos job). The slow acceptance test at the bottom needs no
such ambient setup: it forces virtual device counts on its OWN
subprocesses — an 8-device run is SIGKILLed mid-flight and resumed
under 4 visible devices, bit-exact with the unsharded reference.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from primesim_tpu.chaos import plan as CP
from primesim_tpu.chaos import sites as CS
from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.parallel import sharding
from primesim_tpu.util import diskpressure
from primesim_tpu.util.diskpressure import DiskPressureError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MULTIDEV = len(jax.devices()) >= 2


def _enospc_plan(calls: int, occurrence: int = 1) -> CP.FaultPlan:
    return CP.FaultPlan(seed=0, events=(
        CP.FaultEvent(site="disk.preflight", occurrence=occurrence,
                      action="enospc_window", args=(("calls", calls),)),
    ))


def _revoke_plan(n: int = 1, occurrence: int = 2) -> CP.FaultPlan:
    return CP.FaultPlan(seed=0, events=(
        CP.FaultEvent(site="devices.revoke", occurrence=occurrence,
                      action="revoke", args=(("n", n),)),
    ))


@pytest.fixture(autouse=True)
def _clean_slate():
    yield
    CS.deactivate()
    sharding.restore_devices()
    diskpressure.configure(budget_bytes=None)
    from primesim_tpu.sim import exec_cache

    exec_cache.configure(False)


# ---- the disk-pressure core ----------------------------------------------


def test_preflight_passes_with_free_space(tmp_path):
    before = diskpressure.stats["rejections"]
    diskpressure.preflight(str(tmp_path / "x.npz"), 1024)
    assert diskpressure.stats["rejections"] == before


def test_preflight_ladder_then_backpressure(tmp_path):
    """A window wider than one ladder pass rejects with a typed,
    retryable error; once the window drains, the same write passes."""
    CS.install(_enospc_plan(calls=50))
    with pytest.raises(DiskPressureError) as ei:
        diskpressure.preflight(str(tmp_path / "x.npz"), 1024)
    assert ei.value.retry_after_s > 0
    assert "need_bytes" in ei.value.location()
    CS.deactivate()  # window state dies with the runtime
    diskpressure.preflight(str(tmp_path / "x.npz"), 1024)


def test_cache_budget_feeds_prune(tmp_path, monkeypatch):
    """--cache-budget (diskpressure.configure) outranks the env var in
    prune_warm_cache's budget resolution."""
    from primesim_tpu.sim.checkpoint import prune_warm_cache

    root = tmp_path / "warm"
    root.mkdir()
    for i in range(3):
        p = root / (f"{i:064x}" + ".npz")
        p.write_bytes(b"x" * 4096)
        sc = root / (f"{i:064x}" + ".json")
        sc.write_text(json.dumps({"steps": 1}))
        os.utime(p, (i, i))
    monkeypatch.setenv("PRIMETPU_CACHE_MAX_BYTES", str(1 << 30))
    diskpressure.configure(budget_bytes=5000)  # room for one entry
    prune_warm_cache(str(root))
    left = [n for n in os.listdir(root) if n.endswith(".npz")]
    assert len(left) == 1  # env var alone would have kept all three


# ---- ENOSPC at each governed write site ----------------------------------


def test_journal_append_enospc_no_acked_record_lost(tmp_path):
    """Sustained ENOSPC at journal append: the append either lands or
    raises typed backpressure — never a torn/silent loss — and retries
    succeed once the window drains. Every ACKed record replays."""
    from primesim_tpu.serve.journal import JobJournal

    # no compactor: every surviving record must appear verbatim in the
    # replay (a compacting journal may legally FOLD notes away, which is
    # the compaction rung working, not a loss)
    j = JobJournal(str(tmp_path / "j"))
    j.append({"t": "note", "msg": "pre-pressure"})
    CS.install(_enospc_plan(calls=9))
    acked, rejected = [], 0
    for i in range(10):
        rec = {"t": "note", "msg": f"r{i}"}
        try:
            j.append(rec)
        except DiskPressureError:
            rejected += 1
            continue  # a real client backs off and retries
        acked.append(rec["msg"])
    CS.deactivate()
    j.append({"t": "note", "msg": "post-pressure"})
    j.close()
    assert rejected > 0 and acked  # both sides of the window exercised
    replayed, dropped = JobJournal(str(tmp_path / "j")).replay()
    assert dropped == 0
    msgs = [r["msg"] for r in replayed if r.get("t") == "note"]
    assert msgs.count("pre-pressure") == 1
    assert msgs.count("post-pressure") == 1
    for m in acked:
        assert msgs.count(m) == 1  # ACKed exactly once, never lost


def test_checkpoint_write_enospc_leaves_no_debris(tmp_path):
    """atomic_save_npz preflights before the temp file exists: a
    rejected snapshot write leaves NO partial artifact, and the same
    write succeeds after the pressure clears."""
    from primesim_tpu.sim.checkpoint import atomic_save_npz

    path = str(tmp_path / "ck" / "snap.npz")
    os.makedirs(os.path.dirname(path))
    CS.install(_enospc_plan(calls=50))
    with pytest.raises(DiskPressureError):
        atomic_save_npz(path, a=np.arange(8))
    CS.deactivate()
    assert os.listdir(os.path.dirname(path)) == []  # no .tmp, no torn npz
    atomic_save_npz(path, a=np.arange(8))
    assert os.path.exists(path)


def test_supervised_run_rides_out_checkpoint_enospc(tmp_path):
    """A supervised run whose snapshot rotations ALL hit disk pressure
    still commits every chunk and finishes bit-exact — the rotation is
    skipped with a disk-pressure log line, never a crash."""
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.sim.supervisor import RunSupervisor
    from primesim_tpu.trace import synth

    cfg = small_test_config(8, n_banks=4)
    trace = synth.fft_like(8, n_phases=1, points_per_core=12, seed=3)

    ref = Engine(cfg, trace, chunk_steps=32)
    RunSupervisor(ref, handle_signals=False).run()

    CS.install(_enospc_plan(calls=500))  # outlasts every rotation
    eng = Engine(cfg, trace, chunk_steps=32)
    sup = RunSupervisor(eng, snapshot_dir=str(tmp_path / "snaps"),
                        checkpoint_every_chunks=1, handle_signals=False)
    sup.run()
    CS.deactivate()
    assert sup.checkpoints_written == 0
    assert any(kind == "disk-pressure" for _, kind, _ in sup._events_log)
    np.testing.assert_array_equal(
        np.asarray(eng.cycles), np.asarray(ref.cycles))
    for k, v in eng.counters.items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(ref.counters[k]), err_msg=k)


def test_exec_cache_write_enospc_degrades_to_recompile(tmp_path):
    """ENOSPC at the exec-cache store: the run keeps its freshly
    compiled executable (no committed chunk lost), the save degrades to
    a structured fallback warning, and no cache debris lands."""
    from primesim_tpu.sim import exec_cache
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.trace import synth

    cfg = small_test_config(8, n_banks=4)
    trace = synth.fft_like(8, n_phases=1, points_per_core=12, seed=5)
    ref = Engine(cfg, trace, chunk_steps=32)
    ref.run_chunked(max_steps=10_000_000)

    cache = exec_cache.configure(True, root=str(tmp_path / "exec"))
    CS.install(_enospc_plan(calls=500))
    eng = Engine(cfg, trace, chunk_steps=32)
    eng.run_chunked(max_steps=10_000_000)
    CS.deactivate()
    assert any(w.get("stage") == "save" for w in cache.warnings)
    assert not [n for n in os.listdir(str(tmp_path / "exec"))
                if n.endswith(".tmp")]
    np.testing.assert_array_equal(
        np.asarray(eng.cycles), np.asarray(ref.cycles))


def test_fsck_flags_enospc_debris(tmp_path):
    """fsck: zero-length artifacts and .tmp leftovers are repairable
    orphans; --repair quarantine sweeps them aside (never deletes)."""
    from primesim_tpu.analysis.fsck import run_fsck

    (tmp_path / "empty.npz").write_bytes(b"")
    (tmp_path / "half.tmp").write_bytes(b"torn")
    rep = run_fsck(str(tmp_path))
    kinds = {(f.kind, f.path) for f in rep.findings}
    assert ("orphan", "empty.npz") in kinds
    assert ("orphan", "half.tmp") in kinds
    assert all(f.repairable for f in rep.findings)
    rep2 = run_fsck(str(tmp_path), repair="quarantine")
    assert sorted(rep2.quarantined) == ["empty.npz", "half.tmp"]
    assert (tmp_path / ".fsck-quarantine" / "empty.npz").exists()


# ---- device-loss recovery -------------------------------------------------


def test_classify_device_loss():
    from primesim_tpu.parallel.sharding import DeviceMeshError
    from primesim_tpu.sim.supervisor import classify_failure

    assert classify_failure(RuntimeError("DEVICE_LOST: chip 3")) == \
        "device_loss"
    # DeviceMeshError IS a ValueError; it must classify as device loss,
    # not fall into the never-retry programming-error guard
    assert classify_failure(
        DeviceMeshError("mesh broke", devices=4, visible=2)
    ) == "device_loss"
    assert classify_failure(ValueError("plain bug")) is None


def test_largest_valid_submesh():
    from primesim_tpu.parallel.sharding import (
        DeviceMeshError,
        largest_valid_submesh,
    )

    cfg = MachineConfig(n_cores=8, n_banks=8)
    assert largest_valid_submesh(cfg, 8) == 8
    assert largest_valid_submesh(cfg, 7) == 4
    assert largest_valid_submesh(cfg, 3) == 2
    assert largest_valid_submesh(cfg, 1) == 1
    with pytest.raises(DeviceMeshError):
        largest_valid_submesh(cfg, 0)
    cfg2 = MachineConfig(n_cores=8, n_banks=4)
    assert largest_valid_submesh(cfg2, 8) == 4  # must divide banks too


@pytest.mark.skipif(not MULTIDEV, reason="needs >= 2 visible devices")
def test_supervisor_reshards_after_device_revocation(tmp_path):
    """Seeded revocation at a chunk boundary: the supervisor re-places
    the newest verified snapshot onto the largest valid smaller mesh
    and finishes bit-exact with the unsharded reference."""
    from primesim_tpu.sim.engine import Engine
    from primesim_tpu.sim.supervisor import RunSupervisor
    from primesim_tpu.trace import synth

    cfg = small_test_config(8, n_banks=8)
    trace = synth.fft_like(8, n_phases=1, points_per_core=12, seed=7)

    ref = Engine(cfg, trace, chunk_steps=32)
    RunSupervisor(ref, handle_signals=False).run()

    n = sharding.largest_valid_submesh(cfg, len(jax.devices()))
    mesh = sharding.tile_mesh(devices=jax.devices()[:n])
    eng = Engine(cfg, trace, chunk_steps=32, mesh=mesh)
    sup = RunSupervisor(eng, snapshot_dir=str(tmp_path / "snaps"),
                        checkpoint_every_chunks=1, handle_signals=False)
    CS.install(_revoke_plan(n=1, occurrence=2))
    sup.run()
    CS.deactivate()
    sharding.restore_devices()
    assert sup.degrade_rungs and \
        sup.degrade_rungs[0].startswith(f"reshard:{n}->")
    assert "degrade_rungs" in sup.summary()
    np.testing.assert_array_equal(
        np.asarray(eng.cycles), np.asarray(ref.cycles))
    for k, v in eng.counters.items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(ref.counters[k]), err_msg=k)


@pytest.mark.skipif(not MULTIDEV, reason="needs >= 2 visible devices")
def test_worker_releases_unit_on_shrunken_mesh():
    """A pool worker with revoked devices re-leases a sharded unit onto
    the largest valid smaller mesh and records the granted size on the
    unit (re-keying its geometry bucket) instead of quarantining."""
    from primesim_tpu.pool.worker import PoolWorker

    cfg = small_test_config(8, n_banks=8)
    n = sharding.largest_valid_submesh(cfg, len(jax.devices()))
    w = PoolWorker(socket_path="/nonexistent.sock", worker_id="tw")
    unit = {"devices": n}
    mesh = w._unit_mesh(unit, cfg)
    assert "_granted_devices" not in unit  # full grant, no degrade
    assert len(mesh.devices.flatten()) == n

    sharding.revoke_devices([jax.devices()[n - 1].id])
    unit2 = {"devices": n}
    mesh2 = w._unit_mesh(unit2, cfg)
    granted = unit2["_granted_devices"]
    assert granted == sharding.largest_valid_submesh(cfg, n - 1)
    assert len(mesh2.devices.flatten()) == granted
    assert w.units_degraded == 1
    sharding.restore_devices()


def test_capacity_campaign_invariant_g():
    """A small fixed-seed capacity_loss campaign must fire faults and
    hold invariant G (single-device backends exercise the ENOSPC half;
    multi-device backends the revocation half too)."""
    from primesim_tpu.chaos import campaign as C

    rep = C.run_campaign(n_trials=3, seed0=77,
                         classes=("capacity_loss",), max_events=3)
    assert rep["ok"], rep["violations"]
    assert rep["trials"] == 3
    assert rep["fired_events"] > 0


# ---- acceptance: SIGKILL an 8-device run, resume on 4 --------------------


def _run_cli(argv, n_devices, wait_snapshot_dir=None, kill=None):
    """Run the CLI in a subprocess under a FORCED virtual device count;
    optionally SIGKILL it once a snapshot exists. Returns (rc, stdout)."""
    code = (
        "import sys; from primesim_tpu.cli import main; "
        "sys.exit(main(%r))" % (argv,)
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        XLA_FLAGS=f"--xla_force_host_platform_device_count={n_devices}",
    )
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    try:
        if kill is not None:
            from primesim_tpu.sim.supervisor import SnapshotStore

            deadline = time.time() + 180
            while time.time() < deadline:
                if (os.path.isdir(wait_snapshot_dir)
                        and SnapshotStore(wait_snapshot_dir).snapshots()):
                    break
                if proc.poll() is not None:
                    break
                time.sleep(0.05)
            if proc.poll() is None:
                proc.send_signal(kill)
        out, err = proc.communicate(timeout=300)
    finally:
        proc.kill()
    return proc.returncode, out.decode(), err.decode()


def _run_summary(out):
    """The run summary JSON line (--exec-cache appends a stats line
    after it, so 'last JSON line' is not the summary)."""
    for ln in reversed(out.splitlines()):
        if ln.startswith("{"):
            det = json.loads(ln).get("detail") or {}
            if "instructions" in det:
                return det
    raise AssertionError("no run-summary JSON line in CLI output")


@pytest.mark.slow
def test_kill_8dev_resume_4dev_bit_exact(tmp_path):
    """The headline acceptance: an 8-device sharded supervised run is
    SIGKILLed mid-flight; a restart that can only see 4 devices resumes
    from the surviving snapshot onto the smaller mesh and finishes
    bit-exact with the unsharded reference — with --exec-cache and
    --attest riding along intact."""
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    spec = "fft_like:n_phases=6,points_per_core=96"
    ckdir = str(tmp_path / "ck")
    cache = str(tmp_path / "cache")
    os.environ.setdefault("PRIMETPU_CACHE_DIR", cache)
    base = ["run", cfg_path, "--synth", spec, "--chunk-steps", "8",
            "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
            "--exec-cache", "on", "--attest", "chain"]

    rc, out, err = _run_cli(base + ["--devices", "8"], n_devices=8,
                            wait_snapshot_dir=ckdir, kill=signal.SIGKILL)
    if rc == 0:
        pytest.skip("run finished before SIGKILL could land")
    assert rc == -signal.SIGKILL

    rc, out, err = _run_cli(base + ["--devices", "4", "--resume"],
                            n_devices=4)
    assert rc == 0, err[-2000:]
    resumed = _run_summary(out)
    assert resumed.get("resumed_from"), "resume did not use the snapshot"

    rc, out, err = _run_cli(
        ["run", cfg_path, "--synth", spec, "--chunk-steps", "8"],
        n_devices=1,
    )
    assert rc == 0, err[-2000:]
    ref = _run_summary(out)
    assert resumed["instructions"] == ref["instructions"]
    assert resumed["max_core_cycles"] == ref["max_core_cycles"]
