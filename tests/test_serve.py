"""Tests for the serving subsystem (serve/): journal durability, the job
state machine, continuous-batching bit-exactness, deadlines, quarantine,
backpressure, fairness, crash recovery, the socket protocol, and the CLI
error/exit-code surface.

Shape discipline: almost every test uses small_test_config(4) with a
(2 slots x 1 page) bucket and chunk_steps=16 so the whole file shares
ONE compiled fleet program per process (the serving contract itself).

The subprocess acceptance tests (real `kill -9`, real SIGTERM against a
real daemon) are @slow: tier-1 pins the semantics in-process; the CI
serve-smoke job runs the wiring.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.serve import (
    Job,
    JobJournal,
    JournalCorrupt,
    Scheduler,
    fold_records,
)
from primesim_tpu.serve.scheduler import QueueFull

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMALL_SYNTH = "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed={}"
#: 103 events/core: needs 2 pages (>64, <=128), runs for several chunks
LONG_SYNTH = "fft_like:n_phases=2,points_per_core=24,ins_per_mem=4,seed={}"


def _cfg():
    return small_test_config(4)


def _sched(tmp_path, name="srv", buckets=((2, 1),), **kw):
    d = str(tmp_path / name)
    kw.setdefault("chunk_steps", 16)
    kw.setdefault("max_queue", 16)
    return Scheduler(_cfg(), JobJournal(d), d, buckets=buckets, **kw)


def _job(i, synth=SMALL_SYNTH, **kw):
    return Job(job_id=f"j{i:06d}", synth=synth.format(i), **kw)


def _run_all(sched, jobs, limit=5000):
    n = 0
    while not all(j.terminal for j in jobs):
        sched.tick()
        n += 1
        assert n < limit, [j.state for j in jobs]


def _solo_result(cfg, synth_spec, chunk_steps=16):
    from primesim_tpu.serve.scheduler import parse_synth_spec
    from primesim_tpu.sim.engine import Engine

    eng = Engine(cfg, parse_synth_spec(synth_spec, cfg.n_cores, True),
                 chunk_steps=chunk_steps)
    eng.run()
    return (
        [int(c) for c in eng.cycles],
        {k: [int(x) for x in v] for k, v in eng.counters.items()},
    )


# ---- journal -------------------------------------------------------------


def test_journal_roundtrip_and_torn_tail(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d)
    j.accept(_job(1))
    j.state("j000001", "RUNNING", detail={"attempt": 1})
    j.state("j000001", "DONE", result={"cycles": 42})
    j.close()

    j2 = JobJournal(d)
    recs, dropped = j2.replay()
    assert dropped == 0
    assert [r["t"] for r in recs] == ["accept", "state", "state"]
    jobs, clean = fold_records(recs)
    assert jobs["j000001"].state == "DONE"
    assert jobs["j000001"].result == {"cycles": 42}
    assert not clean

    # a torn TAIL (crash mid-append) is tolerated and reported
    with open(j2.path, "a") as f:
        f.write('{"c": 1, "r": {"t": "accept"')  # no newline, no close
    recs2, dropped2 = JobJournal(d).replay()
    assert len(recs2) == 3 and dropped2 == 1


def test_journal_midfile_corruption_raises(tmp_path):
    d = str(tmp_path / "wal")
    j = JobJournal(d)
    j.note("one")
    j.note("two")
    j.close()
    lines = open(j.path).read().splitlines()
    lines[0] = lines[0].replace("one", "eno")  # CRC now fails, line 2 valid
    with open(j.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(JournalCorrupt):
        JobJournal(d).replay()


def test_journal_ack_is_durable(tmp_path):
    """accept() returns only after the record is on disk: a reopened
    journal (no close/flush on the writer) already sees it."""
    d = str(tmp_path / "wal")
    j = JobJournal(d)
    j.accept(_job(7))
    recs, _ = JobJournal(d).replay()  # writer still open, never closed
    assert recs and recs[0]["job"]["job_id"] == "j000007"


def test_fold_records_first_terminal_wins_under_duplicates():
    """The fold invariants the pool coordinator's first-ACK-wins lease
    protocol leans on: duplicate accepts are ignored, the first terminal
    state is forever, and late RUNNING records (an out-of-order
    redispatch) never demote a finished job."""
    job = _job(3)
    acc = {"t": "accept", "job": job.accept_record()}
    run = {"t": "state", "job_id": job.job_id, "state": "RUNNING"}
    done = {"t": "state", "job_id": job.job_id, "state": "DONE",
            "result": {"cycles": 1}}
    late = {"t": "state", "job_id": job.job_id, "state": "DONE",
            "result": {"cycles": 999}}

    jobs, clean = fold_records([acc, run, done, acc, run, late])
    assert jobs[job.job_id].state == "DONE"
    assert jobs[job.job_id].result == {"cycles": 1}  # first terminal wins
    assert not clean

    # RUNNING at crash (no terminal record) folds back to PENDING
    jobs2, _ = fold_records([acc, run])
    assert jobs2[job.job_id].state == "PENDING"

    # a state record for a never-accepted job is skipped, and a drain
    # marker only counts when it is the LAST thing in the log
    jobs3, clean3 = fold_records([run, acc, {"t": "drain"}])
    assert jobs3[job.job_id].state == "PENDING"
    assert clean3
    assert not fold_records([{"t": "drain"}, acc])[1]


def test_claim_socket_path_unlinks_stale_refuses_live(tmp_path):
    """The stale-socket regression: a SIGKILLed daemon leaves its socket
    inode behind; the next bind must reclaim it — but never steal a
    LIVE listener's path."""
    import socket as socketmod

    from primesim_tpu.serve.protocol import claim_socket_path, socket_alive

    path = str(tmp_path / "srv.sock")
    s = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    s.bind(path)
    s.close()  # bound then dead: the corpse a kill -9 leaves
    assert os.path.exists(path) and not socket_alive(path)
    claim_socket_path(path)
    assert not os.path.exists(path)
    claim_socket_path(path)  # absent path is a no-op

    srv = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
    try:
        srv.bind(path)
        srv.listen(1)
        assert socket_alive(path)
        with pytest.raises(RuntimeError, match="live server"):
            claim_socket_path(path)
        assert os.path.exists(path)  # the running daemon keeps its door
    finally:
        srv.close()


# ---- job state machine ---------------------------------------------------


def test_job_state_machine():
    job = _job(1)
    job.transition("RUNNING")
    job.transition("DONE")
    assert job.terminal and job.latency_s is not None
    with pytest.raises(ValueError):
        job.transition("RUNNING")  # terminal states are sticky

    job2 = _job(2)
    with pytest.raises(ValueError):
        job2.transition("DONE")  # PENDING cannot skip RUNNING
    job2.transition("CANCELLED")
    assert job2.terminal


def test_job_accept_record_roundtrip():
    job = _job(3, deadline_s=9.5, priority=2, client="alice")
    back = Job.from_accept_record(json.loads(json.dumps(job.accept_record())))
    assert back.job_id == job.job_id
    assert back.deadline_s == 9.5
    assert back.priority == 2
    assert back.client == "alice"
    assert back.state == "PENDING"


# ---- scheduler: continuous batching, bit-exactness -----------------------


def test_scheduler_end_to_end_bit_exact(tmp_path):
    """More jobs than slots, drained through the continuous-batching
    loop: every job lands DONE with results identical to a solo Engine
    run of the same (config, trace) — the serving contract."""
    sched = _sched(tmp_path)
    jobs = [_job(i) for i in range(5)]
    for j in jobs:
        sched.submit(j)
    _run_all(sched, jobs)
    assert all(j.state == "DONE" for j in jobs)
    for j in jobs:
        cyc, ctr = _solo_result(sched.cfg, j.synth)
        assert j.result["core_cycles"] == cyc
        assert j.result["counters"] == ctr
    s = sched.stats()
    assert s["completed"] == 5
    assert s["queue_depth"] == 0
    assert s["slots"]["occupied"] == 0
    assert s["latency_s"]["p50"] is not None


def test_scheduler_bucket_routing(tmp_path):
    """A short trace lands in the small bucket even when the big one is
    free; a trace too long for page 1 routes to the larger bucket."""
    sched = _sched(tmp_path, buckets=((2, 1), (1, 2)))
    small, large = _job(1), _job(2, synth=LONG_SYNTH)
    sched.submit(small)
    sched.submit(large)
    assert large._trace.max_len > sched.buckets[0].capacity  # needs 2 pages
    sched.tick()
    assert sched.buckets[0].slots[0] is small
    assert sched.buckets[1].slots[0] is large
    _run_all(sched, [small, large])
    for j in (small, large):
        assert j.state == "DONE"
        cyc, ctr = _solo_result(sched.cfg, j.synth)
        assert j.result["core_cycles"] == cyc
        assert j.result["counters"] == ctr


def test_scheduler_crash_recovery_bit_exact(tmp_path):
    """Abandon a scheduler mid-flight (the in-process kill -9: no drain,
    no close), replay its journal into a fresh one, and finish. Every
    accepted job completes with results identical to an uninterrupted
    run — including the one resumed from its element checkpoint."""
    ref = _sched(tmp_path, "ref")
    refjobs = [_job(i) for i in range(3)]
    for j in refjobs:
        ref.submit(j)
    _run_all(ref, refjobs)

    d = str(tmp_path / "srv")
    s1 = Scheduler(_cfg(), JobJournal(d), d, buckets=((2, 1),),
                   chunk_steps=16, max_queue=16, checkpoint_every_s=0.0)
    jobs1 = [_job(i) for i in range(3)]
    for j in jobs1:
        s1.submit(j)
    for _ in range(3):
        s1.tick()  # some DONE, some mid-flight with checkpoints on disk
    del s1  # crash: journal fd dropped, nothing flushed beyond appends

    wal = JobJournal(d)
    records, dropped = wal.replay()
    assert dropped == 0
    jobs, clean = fold_records(records)
    assert not clean and len(jobs) == 3
    s2 = Scheduler(_cfg(), wal, d, buckets=((2, 1),),
                   chunk_steps=16, max_queue=16)
    for job in jobs.values():
        (s2.adopt_terminal if job.terminal else s2.requeue_recovered)(job)
    _run_all(s2, list(s2.jobs.values()))
    for rj in refjobs:
        got = s2.jobs[rj.job_id]
        assert got.state == "DONE"
        assert got.result["core_cycles"] == rj.result["core_cycles"]
        assert got.result["counters"] == rj.result["counters"]


def test_element_checkpoint_rejected_by_solo_loader(tmp_path):
    """A per-job element checkpoint must not silently load as a solo-run
    snapshot (same format version, different shape contract)."""
    from primesim_tpu.serve.scheduler import parse_synth_spec
    from primesim_tpu.sim.checkpoint import load_checkpoint
    from primesim_tpu.sim.engine import Engine

    sched = _sched(tmp_path, buckets=((2, 2),), checkpoint_every_s=0.0)
    job = Job(job_id="j000001", synth=LONG_SYNTH.format(1))
    sched.submit(job)
    sched.tick()
    ck = sched.job_ckpt_path(job.job_id)
    assert os.path.exists(ck)
    eng = Engine(_cfg(), parse_synth_spec(job.synth, 4, True),
                 chunk_steps=16)
    with pytest.raises(ValueError, match="element checkpoint"):
        load_checkpoint(ck, eng)


# ---- deadlines, budgets, quarantine, backpressure ------------------------


def test_deadline_timeout_in_queue(tmp_path):
    sched = _sched(tmp_path)
    job = _job(1, deadline_s=0.0)  # expired at acceptance
    sched.submit(job)
    sched.tick()
    assert job.state == "TIMEOUT"
    assert "deadline" in job.detail["detail"]


def test_deadline_timeout_while_running(tmp_path):
    sched = _sched(tmp_path, buckets=((2, 2),))
    job = _job(1, synth=LONG_SYNTH, deadline_s=0.05)
    sched.submit(job)
    sched.tick()  # spliced + first chunk
    time.sleep(0.06)
    n = 0
    while not job.terminal:
        sched.tick()
        n += 1
        assert n < 100
    assert job.state == "TIMEOUT"
    assert sched.stats()["slots"]["occupied"] == 0  # slot was reclaimed


def test_step_budget_quarantines(tmp_path):
    sched = _sched(tmp_path, buckets=((2, 2),))
    job = _job(1, synth=LONG_SYNTH, max_steps=16)  # needs far more
    sched.submit(job)
    _run_all(sched, [job], limit=100)
    assert job.state == "QUARANTINED"
    assert job.detail["type"] == "StepBudget"


def test_bad_workload_quarantined_with_structured_error(tmp_path):
    sched = _sched(tmp_path)
    bad = Job(job_id="j000001", synth="no_such_generator:x=1")
    sched.submit(bad)
    assert bad.state == "QUARANTINED"
    assert set(bad.detail) >= {"type", "location", "detail"}
    assert "no_such_generator" in bad.detail["detail"]
    # the terminal record is journaled even though it never ran
    jobs, _ = fold_records(sched.journal.replay()[0])
    assert jobs["j000001"].state == "QUARANTINED"


def test_oversized_trace_quarantined(tmp_path):
    sched = _sched(tmp_path)  # one page = 64 event slots
    big = _job(1, synth=LONG_SYNTH)
    sched.submit(big)
    assert big.state == "QUARANTINED"
    assert big.detail["type"] == "CapacityError"


def test_backpressure_queue_full(tmp_path):
    sched = _sched(tmp_path, max_queue=2)
    sched.submit(_job(1))
    sched.submit(_job(2))
    with pytest.raises(QueueFull) as ei:
        sched.submit(_job(3))
    assert ei.value.retry_after_s > 0
    # the refused job was never ACKed: nothing about it in the journal
    jobs, _ = fold_records(sched.journal.replay()[0])
    assert len(jobs) == 2


def test_cancel_pending_and_unknown(tmp_path):
    sched = _sched(tmp_path)
    job = _job(1)
    sched.submit(job)
    sched.cancel(job.job_id)
    assert job.state == "CANCELLED"
    assert job.job_id not in sched.queue
    with pytest.raises(KeyError):
        sched.cancel("nope")
    with pytest.raises(ValueError):
        sched.cancel(job.job_id)  # already terminal


# ---- fairness / priority -------------------------------------------------


def _running_order(sched):
    """job_ids in the order their RUNNING records hit the journal."""
    recs, _ = sched.journal.replay()
    return [r["job_id"] for r in recs
            if r["t"] == "state" and r["state"] == "RUNNING"]


def test_per_client_fairness(tmp_path):
    """One slot, client A floods, client B submits one job later: B runs
    second, not last — round-robin within the priority tier."""
    sched = _sched(tmp_path, buckets=((1, 1),))
    a = [_job(i, client="a") for i in range(3)]
    b = _job(9, client="b")
    for j in a:
        sched.submit(j)
    sched.submit(b)
    _run_all(sched, a + [b])
    order = _running_order(sched)
    assert order[0] == a[0].job_id  # FIFO among never-picked clients
    assert order[1] == b.job_id     # b has never been picked: beats a's 2nd


def test_priority_beats_accept_order(tmp_path):
    sched = _sched(tmp_path, buckets=((1, 1),))
    lo = _job(1, priority=0)
    hi = _job(2, priority=5)
    sched.submit(lo)
    sched.submit(hi)
    _run_all(sched, [lo, hi])
    assert _running_order(sched)[0] == hi.job_id


# ---- socket server (in-process) ------------------------------------------


def test_server_socket_roundtrip(tmp_path):
    """Full daemon in a worker thread: submit/status/wait/health/cancel
    over the real unix socket, then the drain verb shuts it down with
    exit code 0 (queue ran dry)."""
    import threading

    from primesim_tpu.serve.client import ServeClient, ServeError
    from primesim_tpu.serve.server import PrimeServer

    server = PrimeServer(
        _cfg(), state_dir=str(tmp_path / "srv"), buckets=((2, 1),),
        chunk_steps=16, checkpoint_every_s=60.0,
    )
    rc_box = {}
    t = threading.Thread(
        target=lambda: rc_box.update(rc=server.serve_forever()), daemon=True
    )
    t.start()
    cli = ServeClient(server.socket_path, timeout_s=60.0)
    deadline = time.time() + 60
    while not os.path.exists(server.socket_path):
        assert time.time() < deadline
        time.sleep(0.01)

    job = cli.submit(synth=SMALL_SYNTH.format(3), client="t")
    assert job["job_id"] == "j000001" and job["state"] == "PENDING"
    done = cli.wait(job["job_id"], timeout_s=120.0)
    assert done["state"] == "DONE"
    cyc, ctr = _solo_result(_cfg(), SMALL_SYNTH.format(3))
    assert done["result"]["core_cycles"] == cyc
    assert done["result"]["counters"] == ctr

    health = cli.health()
    assert health["completed"] == 1 and health["queue_depth"] == 0

    with pytest.raises(ServeError, match="unknown job"):
        cli.status("j999999")
    with pytest.raises(ServeError):
        cli.cancel(job["job_id"])  # already terminal

    cli.drain()
    t.join(timeout=120)
    assert not t.is_alive()
    assert rc_box["rc"] == 0  # nothing unfinished at drain


def test_server_backpressure_retry_after_on_wire(tmp_path):
    import threading

    from primesim_tpu.serve.client import ServeClient, ServeError
    from primesim_tpu.serve.server import PrimeServer

    server = PrimeServer(
        _cfg(), state_dir=str(tmp_path / "srv"), buckets=((2, 1),),
        chunk_steps=16, max_queue=1,
    )
    # listener + inbox pump only — NO tick loop, so admitted jobs stay
    # queued and the second submit hits the bound
    listener = server._make_listener()
    t = threading.Thread(target=listener.serve_forever, daemon=True)
    t.start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            server._drain_inbox()
            time.sleep(0.005)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    try:
        cli = ServeClient(server.socket_path, timeout_s=30.0)
        cli.submit(synth=SMALL_SYNTH.format(1))
        with pytest.raises(ServeError) as ei:
            cli.submit(synth=SMALL_SYNTH.format(2))
        assert ei.value.retry_after_s is not None
        assert ei.value.error["type"] == "QueueFull"
    finally:
        stop.set()
        listener.shutdown()
        listener.server_close()


def test_sighup_reload_rejects_geometry_change(tmp_path):
    import dataclasses

    from primesim_tpu.serve.server import PrimeServer

    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        f.write(_cfg().to_json())
    server = PrimeServer(
        _cfg(), state_dir=str(tmp_path / "srv"), buckets=((2, 1),),
        chunk_steps=16, config_path=cfg_path,
    )
    # traced-knob change (fault seed): accepted
    with open(cfg_path, "w") as f:
        f.write(dataclasses.replace(_cfg(), fault_seed=7).to_json())
    server.reload_config()
    assert server.sched.cfg.fault_seed == 7
    # geometry change: rejected, previous config kept serving
    with open(cfg_path, "w") as f:
        f.write(small_test_config(8).to_json())
    server.reload_config()
    assert server.sched.cfg.n_cores == 4
    notes = [r["msg"] for r in server.journal.replay()[0]
             if r["t"] == "note"]
    assert any("REJECTED" in m for m in notes)
    server.journal.close()


# ---- report / stats ------------------------------------------------------


def test_service_report_section(tmp_path):
    from primesim_tpu.stats.counters import COUNTER_NAMES
    from primesim_tpu.stats.report import render_report

    cfg = _cfg()
    txt = render_report(
        cfg,
        {k: np.zeros(cfg.n_cores, np.int64) for k in COUNTER_NAMES},
        np.zeros(cfg.n_cores, np.int64),
        title="primetpu serve",
        service={
            "jobs_completed": 3,
            "jobs_by_state": {"DONE": 3, "TIMEOUT": 1},
            "aggregate_mips": 1.25,
            "latency_s": {"p50": 0.5, "p90": 1.0, "p99": None},
            "uptime_s": 12.0,
        },
    )
    assert "SERVICE" in txt
    assert "jobs completed" in txt and "1.250" in txt
    assert "timeout" in txt and "latency p90" in txt
    assert "p99" not in txt  # None percentiles are omitted


# ---- CLI: structured errors (S2) + sweep exit code (S1) ------------------


def _write_cfg(tmp_path):
    p = str(tmp_path / "cfg.json")
    with open(p, "w") as f:
        f.write(_cfg().to_json())
    return p


def test_cli_run_structured_error_json(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    bad = str(tmp_path / "bad.ptpu")
    with open(bad, "wb") as f:
        f.write(b"definitely not a trace")
    rc = main(["run", cfg, "--trace", bad])
    assert rc == 2
    err_lines = [l for l in capsys.readouterr().err.splitlines()
                 if l.startswith("{")]
    assert err_lines, "expected a structured JSON error line on stderr"
    err = json.loads(err_lines[-1])["error"]
    assert err["type"] == "TraceError"
    assert "bad.ptpu" in err["detail"]
    assert "path" in err["location"]


def test_cli_sweep_partial_exits_3(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    bad = str(tmp_path / "bad.ptpu")
    with open(bad, "wb") as f:
        f.write(b"garbage")
    rc = main(["sweep", cfg, "--trace", bad,
               "--synth", "false_sharing:n_mem_ops=20",
               "--chunk-steps", "16"])
    assert rc == 3  # partial: quarantined element + surviving results
    out = capsys.readouterr()
    lines = [json.loads(l) for l in out.out.splitlines()
             if l.startswith("{")]
    quar = [l for l in lines if l["metric"] == "quarantined"]
    assert len(quar) == 1
    err = quar[0]["detail"]["error"]
    assert set(err) >= {"type", "location", "detail"}
    assert "bad.ptpu" in err["detail"]
    assert [l for l in lines if l["metric"] == "simulated_MIPS"]
    assert "partial" in out.err


def test_cli_submit_requires_running_server(tmp_path, capsys):
    from primesim_tpu.cli import main

    rc = main(["submit", "--socket", str(tmp_path / "nope.sock"),
               "--synth", "uniform:n_mem_ops=1"])
    assert rc == 1


# ---- subprocess acceptance: real kill -9 / SIGTERM (CI serve-smoke) ------


def _spawn_server(tmp_path, state="state", idle_exit=None, extra=()):
    from primesim_tpu.serve.client import ServeClient

    cfg_path = _write_cfg(tmp_path)
    sock = str(tmp_path / state / "serve.sock")
    if os.path.exists(sock):
        os.unlink(sock)  # stale socket from a killed predecessor
    argv = ["serve", cfg_path, "--state-dir", str(tmp_path / state),
            "--buckets", "2x1,1x4", "--chunk-steps", "16",
            "--checkpoint-wall", "0.2", *extra]
    if idle_exit is not None:
        argv += ["--idle-exit", str(idle_exit)]
    code = ("import sys; from primesim_tpu.cli import main; "
            "sys.exit(main(%r))" % (argv,))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    deadline = time.time() + 180
    probe = ServeClient(sock, timeout_s=5.0)
    while True:
        if proc.poll() is not None:
            raise AssertionError(
                "server died at startup: "
                + proc.stderr.read().decode()[-2000:]
            )
        if os.path.exists(sock):
            try:
                probe.health()
                break
            except OSError:
                pass  # bound but not accepting yet
        assert time.time() < deadline, "server never became ready"
        time.sleep(0.1)
    return proc, sock


@pytest.mark.slow
def test_subprocess_kill9_journal_replay_bit_exact(tmp_path):
    """kill -9 the daemon mid-batch; restart on the same state dir. Every
    ACKed job reaches DONE with results identical to solo runs — the
    accepted-jobs-survive-anything contract, against a real process with
    real fsyncs."""
    from primesim_tpu.serve.client import ServeClient

    specs = [SMALL_SYNTH.format(11), SMALL_SYNTH.format(12),
             "fft_like:n_phases=3,points_per_core=32,ins_per_mem=4,seed=13"]
    proc, sock = _spawn_server(tmp_path)
    try:
        cli = ServeClient(sock, timeout_s=60.0)
        ids = [cli.submit(synth=s, client="c")["job_id"] for s in specs]
        # let work start, then kill without any warning
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(j["state"] in ("RUNNING", "DONE")
                   for j in cli.status()):
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        proc.kill()

    proc2, sock2 = _spawn_server(tmp_path, idle_exit=2.0)
    try:
        cli2 = ServeClient(sock2, timeout_s=60.0)
        results = {i: cli2.wait(i, timeout_s=240.0) for i in ids}
        out, err = proc2.communicate(timeout=240)
        assert proc2.returncode == 0, err.decode()[-2000:]
    finally:
        proc2.kill()
    for spec, i in zip(specs, ids):
        assert results[i]["state"] == "DONE", (i, results[i])
        cyc, ctr = _solo_result(_cfg(), spec)
        assert results[i]["result"]["core_cycles"] == cyc
        assert results[i]["result"]["counters"] == ctr


@pytest.mark.slow
def test_subprocess_sigterm_drains_exit75_then_finishes(tmp_path):
    """SIGTERM mid-flight: graceful drain checkpoints in-flight jobs and
    exits 75 (EX_TEMPFAIL); a restarted daemon finishes them bit-exact —
    the same preemption contract the supervisor gives solo runs."""
    from primesim_tpu.serve.client import ServeClient

    spec = "fft_like:n_phases=3,points_per_core=32,ins_per_mem=4,seed=21"
    proc, sock = _spawn_server(tmp_path)
    try:
        cli = ServeClient(sock, timeout_s=60.0)
        job_id = cli.submit(synth=spec)["job_id"]
        deadline = time.time() + 120
        while time.time() < deadline:
            if cli.status(job_id)["state"] == "RUNNING":
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        proc.kill()
    if rc == 0:  # the job finished before the signal landed
        pytest.skip("job completed before SIGTERM; nothing to drain")
    assert rc == 75, proc.stderr.read().decode()[-2000:]

    proc2, sock2 = _spawn_server(tmp_path, idle_exit=2.0)
    try:
        cli2 = ServeClient(sock2, timeout_s=60.0)
        done = cli2.wait(job_id, timeout_s=240.0)
        proc2.communicate(timeout=240)
    finally:
        proc2.kill()
    assert done["state"] == "DONE"
    cyc, ctr = _solo_result(_cfg(), spec)
    assert done["result"]["core_cycles"] == cyc
    assert done["result"]["counters"] == ctr
