"""Content-addressed AOT executable cache + overlapped dispatch
(ISSUE 17, DESIGN.md §23).

The contract: with `--exec-cache on` every jitted entry point
(solo/fleet/stream) is compiled once, serialized to
`$PRIMETPU_CACHE_DIR/exec/<key>.bin`, and every later process with the
same geometry deserializes instead of compiling — and the simulation is
BIT-EXACT with the freshly-jitted path, leaf for leaf, across timing
knobs, fault schedules, prefix forks, sharded meshes and kill→resume.
A corrupt/stale/unusable entry degrades to miss-and-recompile with a
structured warning; the cache can make a run faster, never wrong, and
never dead. `--overlap on` speculatively dispatches chunk k+1 while the
host works on chunk k and must be bit-exact with `--overlap off`.
"""

import dataclasses
import json
import os
import signal

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    FAULT_LINK_DEGRADE,
    small_test_config,
)
from primesim_tpu.sim import exec_cache
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.exec_cache import (
    ExecCache,
    exec_key,
    exec_key_payload,
)
from primesim_tpu.sim.fleet import FleetEngine
from primesim_tpu.sim.prefix import execute_prefix_plan, plan_prefix
from primesim_tpu.sim.supervisor import Preempted, RunSupervisor
from primesim_tpu.trace import synth

CHUNK = 16


@pytest.fixture(autouse=True)
def _deactivate_after():
    """Tests flip the process-global cache on; never leak it."""
    yield
    exec_cache.configure(False)


def _cfg(**kw):
    kw.setdefault("quantum", 200)
    return small_test_config(8, n_banks=4, **kw)


def _trace(seed=41):
    return synth.fft_like(8, n_phases=2, points_per_core=12, seed=seed)


def _full_state_equal(a, b):
    for k in a._fields:
        va, vb = getattr(a, k), getattr(b, k)
        if hasattr(va, "_fields"):
            _full_state_equal(va, vb)
            continue
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=k
        )


def _same_results(eng, ref):
    np.testing.assert_array_equal(eng.cycles, ref.cycles)
    for k, v in ref.counters.items():
        np.testing.assert_array_equal(eng.counters[k], v, err_msg=k)
    _full_state_equal(eng.state, ref.state)


def _payload(cfg, chunk=CHUNK, entry="engine.run_chunk", has_sync=True,
             trace=None):
    eng = Engine(cfg, trace if trace is not None else _trace(),
                 chunk_steps=chunk)
    payload, _ = exec_key_payload(
        entry, (cfg, chunk), (eng.events, eng.state),
        {"has_sync": has_sync},
    )
    return payload


# ---- key derivation --------------------------------------------------------


def test_key_sensitive_to_geometry_statics_and_entry():
    base = _payload(_cfg())
    # geometry: different machine -> different key
    big = small_test_config(16, n_banks=4, quantum=200)
    assert exec_key(base) != exec_key(
        _payload(big, trace=synth.fft_like(16, n_phases=2,
                                           points_per_core=12, seed=41)))
    # statics: chunk cadence is baked into the loop bound
    assert exec_key(base) != exec_key(_payload(_cfg(), chunk=32))
    # static kwargs: has_sync selects a different graph
    assert exec_key(base) != exec_key(_payload(_cfg(), has_sync=False))
    # entry name partitions the pool
    assert exec_key(base) != exec_key(
        _payload(_cfg(), entry="engine.run_loop"))


def test_key_invariant_to_traced_timing_knobs():
    """Timing knobs ride in state.knobs (traced), so every timing
    variant of one geometry shares one executable — the same contract
    FleetEngine's geom_cfg static already relies on."""
    base = _payload(_cfg())
    for kw in ({"quantum": 900}, {"dram_lat": 7}):
        variant = _payload(_cfg(**kw))
        assert exec_key(base) == exec_key(variant), kw


def test_key_payload_carries_toolchain_and_formats():
    p = _payload(_cfg())
    for field in ("jax", "jaxlib", "backend", "devices",
                  "exec_format", "ckpt_format", "geom", "tree", "avals"):
        assert field in p, field


# ---- solo engine: bit-exact, disk round trip, fallbacks --------------------


def test_solo_bit_exact_and_fresh_process_disk_hit(tmp_path):
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()

    root = str(tmp_path / "exec")
    cache = exec_cache.configure(True, root=root)
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run()
    _same_results(eng, ref)
    assert cache.stats["misses"] >= 1 and cache.stats["errors"] == 0
    bins = [f for f in os.listdir(root) if f.endswith(".bin")]
    assert bins, "miss must persist an entry"
    # every .bin has its key-payload sidecar
    for b in bins:
        assert os.path.exists(os.path.join(root, b[:-4] + ".json"))

    # a fresh ExecCache == a fresh process: no memo, loads from disk
    cache2 = exec_cache.configure(True, root=root)
    eng2 = Engine(cfg, tr, chunk_steps=CHUNK)
    eng2.run()
    _same_results(eng2, ref)
    assert cache2.stats["hits"] >= 1
    assert cache2.stats["misses"] == 0
    assert cache2.stats["compile_wall_s"] == 0.0


def test_corrupt_entry_degrades_to_recompile(tmp_path):
    cfg, tr = _cfg(), _trace()
    root = str(tmp_path / "exec")
    exec_cache.configure(True, root=root)
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()

    for name in os.listdir(root):
        if name.endswith(".bin"):
            path = os.path.join(root, name)
            blob = bytearray(open(path, "rb").read())
            blob[20] ^= 0xFF
            open(path, "wb").write(bytes(blob))

    cache = exec_cache.configure(True, root=root)
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run()
    _same_results(eng, ref)
    assert cache.stats["errors"] >= 1
    assert cache.stats["misses"] >= 1  # recompiled
    assert any(w["stage"] == "load" and "CRC" in w["error"]
               for w in cache.warnings)


def test_truncated_and_bad_magic_entries(tmp_path):
    cfg, tr = _cfg(), _trace()
    root = str(tmp_path / "exec")
    exec_cache.configure(True, root=root)
    Engine(cfg, tr, chunk_steps=CHUNK).run()

    paths = [os.path.join(root, f) for f in os.listdir(root)
             if f.endswith(".bin")]
    open(paths[0], "wb").write(b"NOTEXEC!")
    cache = exec_cache.configure(True, root=root)
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run()
    assert cache.stats["errors"] >= 1
    assert any(w["stage"] == "load" for w in cache.warnings)
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()
    _same_results(eng, ref)


def test_persist_failure_still_runs(tmp_path, monkeypatch):
    """serialize() blowing up must not take the run down — the compiled
    executable still serves this process; only persistence degrades."""
    import jax.experimental.serialize_executable as se

    def boom(exe):
        raise RuntimeError("no serialization on this backend")

    monkeypatch.setattr(se, "serialize", boom)
    cfg, tr = _cfg(), _trace()
    cache = exec_cache.configure(True, root=str(tmp_path / "exec"))
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run()
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()
    _same_results(eng, ref)
    assert any(w["stage"] == "save" for w in cache.warnings)
    root = str(tmp_path / "exec")
    assert not os.path.isdir(root) or not [
        f for f in os.listdir(root) if f.endswith(".bin")
    ]


def test_inactive_cache_is_a_tail_call():
    """With no cache configured, exec_cache.call is byte-identical to
    calling the jitted fn directly."""
    exec_cache.configure(False)
    seen = {}

    def fake(cfg, chunk, ev, st, has_sync=False):
        seen["args"] = (cfg, chunk, ev, st, has_sync)
        return "out"

    out = exec_cache.call(fake, "engine.run_chunk", ("CFG", 16),
                          ("EV", "ST"), {"has_sync": True})
    assert out == "out"
    assert seen["args"] == ("CFG", 16, "EV", "ST", True)


# ---- composes with faults, timing variants, fleets -------------------------


@pytest.mark.slow
def test_faulted_run_bit_exact(tmp_path):
    cfg = dataclasses.replace(
        _cfg(),
        faults_enabled=True,
        max_fault_events=1,
        fault_events=((40, FAULT_LINK_DEGRADE, 0, 3),),
    )
    tr = _trace()
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()
    exec_cache.configure(True, root=str(tmp_path / "exec"))
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run()
    _same_results(eng, ref)


def test_timing_variants_share_one_entry(tmp_path):
    """Two timing variants of one geometry: one compile, both bit-exact
    vs their own jitted references."""
    tr = _trace()
    cfgs = [_cfg(), _cfg(quantum=900, dram_lat=60)]
    refs = []
    for cfg in cfgs:
        r = Engine(cfg, tr, chunk_steps=CHUNK)
        r.run()
        refs.append(r)

    cache = exec_cache.configure(True, root=str(tmp_path / "exec"))
    for cfg, ref in zip(cfgs, refs):
        eng = Engine(cfg, tr, chunk_steps=CHUNK)
        eng.run()
        _same_results(eng, ref)
    assert cache.stats["misses"] == 1  # second variant reused the entry
    assert cache.stats["memo_hits"] >= 1


@pytest.mark.slow
def test_fleet_warm_exec_and_bit_exact(tmp_path):
    cfg = _cfg()
    traces = [_trace(45), synth.false_sharing(8, n_mem_ops=40, seed=47)]
    ovs = [{}, {"llc_lat": 25}]
    ref = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    ref.run()

    fleet0 = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    assert fleet0.warm_exec() is False  # no cache configured -> no-op

    cache = exec_cache.configure(True, root=str(tmp_path / "exec"))
    fleet = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK)
    assert fleet.warm_exec() is True  # lease-grant warm: compiles now
    assert cache.stats["misses"] == 1
    fleet.run()
    np.testing.assert_array_equal(fleet.cycles, ref.cycles)
    for k, v in ref.counters.items():
        np.testing.assert_array_equal(fleet.counters[k], v, err_msg=k)
    _full_state_equal(fleet.state, ref.state)


# ---- overlapped dispatch ---------------------------------------------------


def test_overlap_bit_exact_solo_and_fleet():
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run_steps(6 * CHUNK)

    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.overlap = True
    eng.run_steps(6 * CHUNK)
    _same_results(eng, ref)

    traces = [_trace(45), _trace(46)]
    fref = FleetEngine(cfg, traces, [{}, {}], chunk_steps=CHUNK)
    fref.run_steps(6 * CHUNK)
    fleet = FleetEngine(cfg, traces, [{}, {}], chunk_steps=CHUNK)
    fleet.overlap = True
    fleet.run_steps(6 * CHUNK)
    np.testing.assert_array_equal(fleet.cycles, fref.cycles)
    _full_state_equal(fleet.state, fref.state)


def test_overlap_discard_on_state_surgery():
    """Anything that reassigns eng.state (checkpoint restore, retry)
    invalidates the speculated chunk — identity check + explicit
    discard_prefetch both cover it."""
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.overlap = True
    eng.run_steps(2 * CHUNK)
    assert eng._pending is not None
    saved = eng.state
    eng.discard_prefetch()
    assert eng._pending is None
    # and the identity guard alone: a stale pending for a different
    # state object must not be consumed
    eng._pending = (object(), "bogus", CHUNK)
    eng.run_steps(CHUNK)
    assert eng.state is not saved  # simulation advanced past the bogus


def test_overlap_preempt_resume_bit_exact(tmp_path):
    """kill -TERM at a chunk boundary with overlap+cache on; the resumed
    run (also overlap+cache) is bit-exact with a plain uninterrupted
    run."""
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()

    exec_cache.configure(True, root=str(tmp_path / "exec"))
    kills = {"n": 0}

    def _kill(sup):
        kills["n"] += 1
        if kills["n"] == 2:
            os.kill(os.getpid(), signal.SIGTERM)

    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.overlap = True
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path / "snap"),
        checkpoint_every_chunks=1, guard="fail", on_chunk=_kill,
    )
    with pytest.raises(Preempted):
        sup.run()
    assert not eng.done()

    eng2 = Engine(cfg, tr, chunk_steps=CHUNK)
    eng2.overlap = True
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path / "snap"),
                         guard="fail")
    assert sup2.resume() is not None
    sup2.run()
    _same_results(eng2, ref)


# ---- heavier compositions: sharded mesh, prefix fork (CI job runs these) --


@pytest.mark.slow
def test_sharded_fleet_cache_bit_exact(tmp_path):
    from primesim_tpu.parallel.sharding import tile_mesh

    cfg = _cfg()
    traces = [_trace(50 + i) for i in range(4)]
    ovs = [{"fault_seed": 7 + i} for i in range(4)]
    ref = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK,
                      mesh=tile_mesh(4))
    ref.run()

    cache = exec_cache.configure(True, root=str(tmp_path / "exec"))
    fleet = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK,
                        mesh=tile_mesh(4))
    fleet.run()
    np.testing.assert_array_equal(fleet.cycles, ref.cycles)
    _full_state_equal(fleet.state, ref.state)
    assert cache.stats["errors"] == 0
    # the sharded entry is addressable: a fresh cache hits from disk
    cache2 = exec_cache.configure(True, root=str(tmp_path / "exec"))
    again = FleetEngine(cfg, traces, ovs, chunk_steps=CHUNK,
                        mesh=tile_mesh(4))
    again.run()
    np.testing.assert_array_equal(again.cycles, ref.cycles)
    assert cache2.stats["hits"] >= 1 and cache2.stats["misses"] == 0


@pytest.mark.slow
def test_prefix_fork_composes_with_cache(tmp_path):
    cfg = dataclasses.replace(
        _cfg(),
        faults_enabled=True,
        max_fault_events=1,
        fault_events=((40, FAULT_LINK_DEGRADE, 0, 3),),
    )
    tr = _trace()
    ovs = [{"fault_seed": 100 + i} for i in range(4)]
    plain = FleetEngine(cfg, [tr] * 4, ovs, chunk_steps=CHUNK)
    plain.run()

    exec_cache.configure(True, root=str(tmp_path / "exec"))
    forked = FleetEngine(cfg, [tr] * 4, ovs, chunk_steps=CHUNK)
    groups = plan_prefix(forked.elem_cfgs, forked.traces, chunk_steps=CHUNK)
    assert groups and groups[0].prefix_steps > 0
    st = execute_prefix_plan(forked, groups)
    assert st["forked_elements"] == 4
    forked.run()
    np.testing.assert_array_equal(forked.cycles, plain.cycles)
    for k, v in plain.counters.items():
        np.testing.assert_array_equal(forked.counters[k], v, err_msg=k)
    _full_state_equal(forked.state, plain.state)


# ---- stream engine ---------------------------------------------------------


def test_stream_engine_bit_exact(tmp_path):
    from primesim_tpu.ingest.stream import StreamEngine

    cfg = _cfg()
    tr = synth.false_sharing(8, n_mem_ops=40, seed=44)
    ref = Engine(cfg, tr, chunk_steps=CHUNK)
    ref.run()

    exec_cache.configure(True, root=str(tmp_path / "exec"))
    eng = StreamEngine(cfg, tr, window_events=8)
    eng.run()
    np.testing.assert_array_equal(eng.cycles, ref.cycles)
    for k, v in ref.counters.items():
        np.testing.assert_array_equal(eng.counters[k], v, err_msg=k)


# ---- shared LRU budget -----------------------------------------------------


def test_shared_lru_budget_spans_warm_and_exec(tmp_path):
    from primesim_tpu.sim.checkpoint import prune_warm_cache

    root = str(tmp_path)
    exec_root = os.path.join(root, "exec")
    os.makedirs(exec_root)

    def put(path, size, mtime):
        with open(path, "wb") as f:
            f.write(b"x" * size)
        json_twin = path[: path.rfind(".")] + ".json"
        with open(json_twin, "w") as f:
            f.write("{}")
        os.utime(path, (mtime, mtime))

    put(os.path.join(root, "warm-old.npz"), 400, 1000)
    put(os.path.join(exec_root, "exec-old.bin"), 400, 2000)
    put(os.path.join(root, "warm-new.npz"), 400, 3000)
    put(os.path.join(exec_root, "exec-new.bin"), 400, 4000)

    removed = prune_warm_cache(root, max_bytes=900)
    assert removed == 2
    # LRU across BOTH pools: the two oldest went, one from each
    assert not os.path.exists(os.path.join(root, "warm-old.npz"))
    assert not os.path.exists(os.path.join(exec_root, "exec-old.bin"))
    assert os.path.exists(os.path.join(root, "warm-new.npz"))
    assert os.path.exists(os.path.join(exec_root, "exec-new.bin"))
    # sidecars go with their entries
    assert not os.path.exists(os.path.join(exec_root, "exec-old.json"))
    assert os.path.exists(os.path.join(exec_root, "exec-new.json"))


def test_write_entry_prunes(tmp_path, monkeypatch):
    """A compile that lands a new .bin immediately re-applies the shared
    budget (so the cache tree cannot grow unbounded between runs)."""
    monkeypatch.setenv("PRIMETPU_CACHE_MAX_BYTES", "1")
    root = str(tmp_path / "warm" / "exec")
    os.makedirs(os.path.dirname(root), exist_ok=True)
    cache = exec_cache.configure(True, root=root)
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=CHUNK)
    eng.run_steps(CHUNK)
    assert cache.stats["misses"] >= 1
    # with a 1-byte budget the entry was pruned right after the write —
    # and the run still completed (the executable is memo-resident)
    assert not [f for f in os.listdir(root) if f.endswith(".bin")]


# ---- fsck integration ------------------------------------------------------


def test_fsck_checks_exec_entries(tmp_path):
    from primesim_tpu.analysis.fsck import run_fsck

    root = str(tmp_path / "exec")
    exec_cache.configure(True, root=root)
    cfg, tr = _cfg(), _trace()
    Engine(cfg, tr, chunk_steps=CHUNK).run_steps(CHUNK)
    bins = [f for f in os.listdir(root) if f.endswith(".bin")]
    assert bins

    res = run_fsck(str(tmp_path))
    assert res.checked["exec_entries"] == len(bins)
    assert res.clean and not res.findings

    # corrupt one: fsck flags it, --repair quarantines it (move aside)
    victim = os.path.join(root, bins[0])
    blob = bytearray(open(victim, "rb").read())
    blob[20] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    res = run_fsck(str(tmp_path))
    assert any(f.kind == "exec-cache" and f.corrupt for f in res.findings)
    res = run_fsck(str(tmp_path), repair="quarantine")
    assert not os.path.exists(victim)
    assert os.path.exists(os.path.join(
        str(tmp_path), ".fsck-quarantine", "exec", bins[0]))


def test_fsck_exec_sidecar_key_content_agreement(tmp_path):
    from primesim_tpu.analysis.fsck import run_fsck

    root = str(tmp_path / "exec")
    exec_cache.configure(True, root=root)
    Engine(_cfg(), _trace(), chunk_steps=CHUNK).run_steps(CHUNK)
    bins = [f for f in os.listdir(root) if f.endswith(".bin")]
    sidecar = os.path.join(root, bins[0][:-4] + ".json")

    with open(sidecar) as f:
        meta = json.load(f)
    good_payload = dict(meta["payload"])

    # edit the payload: it no longer hashes to the entry's address
    meta["payload"]["entry"] = "tampered"
    with open(sidecar, "w") as f:
        json.dump(meta, f)
    res = run_fsck(str(tmp_path))
    assert any(
        f.kind == "exec-cache" and f.corrupt and "hash" in f.detail
        for f in res.findings
    )
    with open(sidecar, "w") as f:  # restore
        json.dump({"key": meta["key"], "payload": good_payload}, f)

    # a toolchain drift is a NOTE (dead address, plain miss), never
    # corrupt: fabricate an entry correctly addressed under another jax
    drifted = dict(good_payload, jax="0.0.1", jaxlib="0.0.1")
    key2 = exec_cache.exec_key(drifted)
    with open(os.path.join(root, bins[0]), "rb") as f:
        body = f.read()
    with open(os.path.join(root, key2 + ".bin"), "wb") as f:
        f.write(body)
    with open(os.path.join(root, key2 + ".json"), "w") as f:
        json.dump({"key": key2, "payload": drifted}, f)
    res = run_fsck(str(tmp_path))
    drift = [f for f in res.findings
             if f.kind == "exec-cache" and "toolchain" in f.detail]
    assert drift and not any(f.corrupt for f in drift)
    assert res.clean
