"""Pallas reduction kernel (ops/reductions.py — SURVEY.md §2 #4's Pallas
uncore piece): the engine's dense sharer-expansion reductions routed
through one Pallas kernel must stay BIT-EXACT against the golden model
on the same workloads that prove the jnp path (interpreter mode on CPU,
compiled on TPU)."""

import numpy as np
import pytest

from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.trace import synth

from test_parity import assert_parity


@pytest.mark.parametrize(
    "gen", ["false_sharing", "uniform_random", "readers_writer"]
)
def test_parity_pallas_reduce(gen):
    cfg = small_test_config(8, n_banks=4, quantum=400, pallas_reduce=True)
    tr = {
        "false_sharing": lambda: synth.false_sharing(8, n_mem_ops=40, seed=41),
        "uniform_random": lambda: synth.uniform_random(8, n_mem_ops=50, seed=42),
        "readers_writer": lambda: synth.readers_writer(8, n_rounds=3, seed=43),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=32)


def test_parity_pallas_reduce_64core():
    # multi-block grid (BC=... rows per kernel instance), word-boundary
    # sharer sets, back-invalidations under a tiny LLC
    from primesim_tpu.config.machine import CacheConfig, NocConfig

    cfg = MachineConfig(
        n_cores=64, n_banks=16,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=4, mesh_y=4),
        quantum=500, pallas_reduce=True,
    )
    assert_parity(
        cfg, synth.readers_writer(64, n_rounds=2, block_lines=4, seed=44),
        chunk_steps=32,
    )


def test_pallas_reduce_rejects_non_dense_modes():
    with pytest.raises(ValueError, match="pallas_reduce"):
        small_test_config(8, pallas_reduce=True, sharer_group=4)
    with pytest.raises(ValueError, match="pallas_reduce"):
        MachineConfig(
            n_cores=64, n_banks=16, pallas_reduce=True, sharer_chunk_words=1
        )
