"""Direct unit tests for stats/report.py: exact golden text for a tiny
hand-built counter set (previously only covered indirectly through the
CLI) and the zero-total `_rate` edge case."""

import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.stats.counters import COUNTER_NAMES
from primesim_tpu.stats.report import _rate, render_report, write_report


def _counters(C, **overrides):
    c = {k: np.zeros(C, dtype=np.int64) for k in COUNTER_NAMES}
    for k, v in overrides.items():
        c[k] = np.asarray(v, dtype=np.int64)
    return c


def test_rate_zero_total_is_na():
    assert _rate(0, 0) == "    n/a"
    assert _rate(5, 0) == "    n/a"  # never divides by zero
    assert _rate(1, 4) == " 25.00%"
    assert _rate(3, 3) == "100.00%"


def test_render_report_golden():
    cfg = small_test_config(2, n_banks=2, quantum=500)
    counters = _counters(
        2,
        instructions=[600, 400],
        l1_read_hits=[30, 10],
        l1_read_misses=[10, 0],  # core 1: no reads missed -> 100.00%
        l1_write_hits=[0, 0],
        l1_write_misses=[0, 0],  # core 0/1: no writes at all -> n/a
        llc_hits=[5, 0],
        llc_misses=[5, 0],  # core 1: no LLC accesses -> n/a
        dram_accesses=[5, 0],
        noc_msgs=[20, 8],
        noc_hops=[40, 16],
        noc_contention_cycles=[12, 3],
        dram_queue_cycles=[7, 0],
    )
    cycles = np.array([2000, 1000], dtype=np.int64)
    text = render_report(cfg, counters, cycles, wall_s=0.5)
    lines = text.splitlines()

    assert lines[0] == "=" * 72
    assert lines[1] == "primesim_tpu simulation report"
    assert "machine: 2 cores, 2 LLC banks, 2x2 mesh, quantum 500" in text
    assert "l1: 1024B 2w lat 2 | llc/bank: 4096B 4w lat 10 | " in text
    assert "  instructions                   1,000" in text
    assert "  max core cycles                2,000" in text
    # IPC = 1000 / (2000 * 2)
    assert "  IPC (agg/core/cyc)            0.2500" in text
    assert "  host wall seconds               0.50" in text
    assert "  simulated MIPS                 0.002" in text
    assert "  L1 read hit rate              80.00%" in text  # 40/50
    assert "  L1 write hit rate                n/a" in text  # zero total
    assert "  LLC hit rate                  50.00%" in text  # 5/10
    assert "  DRAM accesses                      5" in text
    assert "  NoC messages                      28" in text
    assert "  NoC contention cyc                15" in text
    assert "  DRAM queue cycles                  7" in text
    # no sync activity -> the lock/barrier block is omitted entirely
    assert "lock acquires" not in text
    assert "PER-CORE (first 2 of 2)" in text
    core_rows = [ln for ln in lines if ln.startswith("     ")]
    assert core_rows[0] == (
        "     0               600           2,000   0.300"
        "   75.00%      n/a   50.00%"
    )
    assert core_rows[1] == (
        "     1               400           1,000   0.400"
        "  100.00%      n/a      n/a"
    )
    assert lines[-1] == "=" * 72
    assert text.endswith("=" * 72 + "\n")


def test_render_report_sync_block_and_limit():
    cfg = small_test_config(4, n_banks=4)
    counters = _counters(
        4,
        instructions=[100, 100, 100, 100],
        lock_acquires=[2, 0, 0, 0],
        lock_spins=[7, 0, 0, 0],
        barrier_waits=[1, 1, 1, 1],
    )
    cycles = np.full(4, 300, dtype=np.int64)
    text = render_report(
        cfg, counters, cycles, per_core_limit=2, title="custom title"
    )
    assert "custom title" in text
    assert "  lock acquires                      2" in text
    assert "  lock spins                         7" in text
    assert "  barrier waits                      4" in text
    assert "PER-CORE (first 2 of 4)" in text
    assert len([ln for ln in text.splitlines()
                if ln.startswith("     ")]) == 2
    # no wall_s -> no host-time or MIPS lines
    assert "host wall seconds" not in text and "MIPS" not in text


def test_write_report_roundtrip(tmp_path):
    cfg = small_test_config(2, n_banks=2)
    counters = _counters(2, instructions=[1, 1])
    cycles = np.array([10, 10], dtype=np.int64)
    p = str(tmp_path / "r.txt")
    write_report(p, cfg, counters, cycles, title="t")
    with open(p) as f:
        assert f.read() == render_report(cfg, counters, cycles, title="t")
