"""Tests for the telemetry subsystem (obs/, DESIGN.md §15): the metric
ring buffer, the Chrome trace-event flight recorder (schema-validated:
required fields, per-tid monotonic timestamps, balanced B/E spans),
obs-off/obs-on bit-exactness against the fused engine paths, supervisor
event mirroring, the serve `metrics` verb's Prometheus text, the
enriched `health` verb, and the report TIMELINE section.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.obs import Histogram, MetricStore, Recorder, TraceWriter
from primesim_tpu.obs.prom import render_prometheus
from primesim_tpu.serve import Job, JobJournal, Scheduler
from primesim_tpu.serve.scheduler import parse_synth_spec
from primesim_tpu.sim.engine import Engine

SMALL_SYNTH = "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed={}"


def _cfg():
    return small_test_config(4)


def _trace(seed=1):
    return parse_synth_spec(SMALL_SYNTH.format(seed), 4, True)


# ---- MetricStore ---------------------------------------------------------


def test_metric_store_ring_and_deltas():
    st = MetricStore(capacity=3)
    for i in range(5):
        st.record(100.0 + i, "engine", 16, 0.01 * (i + 1),
                  {"instructions": 10 * (i + 1)})
    assert len(st) == 3
    assert st.seq == 5
    assert st.dropped == 2
    # ring keeps the NEWEST samples, seq keeps counting globally
    assert [s["seq"] for s in st.samples()] == [2, 3, 4]
    assert st.samples()[-1]["deltas"]["instructions"] == 50


def test_metric_store_summary():
    st = MetricStore()
    st.record(0.0, "engine", 16, 0.001, {"instructions": 1000})  # 1.0 MIPS
    st.record(0.0, "engine", 16, 0.004, {"instructions": 1000})  # 0.25
    s = st.summary()
    assert s["chunks"] == 2
    assert s["peak_chunk_seq"] == 0
    assert s["peak_chunk_mips"] == pytest.approx(1.0)
    assert s["slowest_chunk_seq"] == 1
    assert s["slowest_chunk_wall_s"] == pytest.approx(0.004)
    # mean = total ins / total wall
    assert s["mean_chunk_mips"] == pytest.approx(2000 / 0.005 / 1e6)
    assert MetricStore().summary() is None


def test_metric_store_jsonl_roundtrip(tmp_path):
    st = MetricStore()
    st.record(1.5, "engine", 16, 0.01, {"instructions": 42},
              phases={"drain": 0.008})
    p = str(tmp_path / "m.jsonl")
    assert st.dump_jsonl(p) == 1
    lines = [json.loads(ln) for ln in open(p)]
    assert lines[0]["deltas"]["instructions"] == 42
    assert lines[0]["phases"]["drain"] == pytest.approx(0.008)


def test_histogram_cumulative_shape():
    h = Histogram(bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["cumulative"] == [1, 3, 4]  # <=0.1, <=1, <=10
    assert snap["count"] == 5  # +Inf bucket covers the 50.0
    assert snap["sum"] == pytest.approx(56.05)
    with pytest.raises(ValueError):
        Histogram(bounds=(1.0, 1.0))


# ---- trace-event schema --------------------------------------------------


def _validate_trace(events):
    """The schema contract: required fields on every event, per-tid
    non-decreasing ts, balanced + alternating B/E per tid."""
    assert events, "trace must not be empty"
    last_ts: dict = {}
    open_spans: dict = {}
    for ev in events:
        for field in ("ph", "ts", "pid", "tid", "name"):
            assert field in ev, f"missing {field!r} in {ev}"
        assert ev["ph"] in ("B", "E", "X", "i", "M"), ev
        tid = ev["tid"]
        if ev["ph"] == "M":
            continue
        assert ev["ts"] >= last_ts.get(tid, 0), (
            f"ts went backwards on tid {tid}: {ev}"
        )
        last_ts[tid] = ev["ts"]
        if ev["ph"] == "B":
            assert tid not in open_spans, f"nested B on tid {tid}"
            open_spans[tid] = ev["name"]
        elif ev["ph"] == "E":
            assert open_spans.pop(tid, None) == ev["name"], (
                f"unbalanced E on tid {tid}: {ev}"
            )
    assert not open_spans, f"unclosed spans: {open_spans}"


def test_trace_writer_schema():
    tw = TraceWriter()
    tw.complete("engine", "chunk", 0.01, {"steps": 16})
    tw.instant("supervisor", "checkpoint", {"msg": "ckpt-1"})
    tw.complete("engine", "chunk", 0.02)
    tw.complete("journal", "fsync", 0.001)
    _validate_trace(tw.events)
    names = {e["args"]["name"] for e in tw.events if e["ph"] == "M"}
    assert names == {"engine", "supervisor", "journal"}


def test_trace_writer_clamps_overlapping_spans():
    tw = TraceWriter()
    # a duration far longer than the writer has been alive would start
    # at negative ts; the clamp keeps it at >= 0 and monotonic
    tw.complete("engine", "chunk", 1e6)
    tw.complete("engine", "chunk", 1e6)
    _validate_trace(tw.events)
    assert all(e["ts"] >= 0 for e in tw.events)


def test_trace_writer_file(tmp_path):
    tw = TraceWriter()
    tw.complete("engine", "chunk", 0.01)
    p = str(tmp_path / "t.json")
    tw.write(p)
    doc = json.load(open(p))
    assert "traceEvents" in doc
    _validate_trace(doc["traceEvents"])


def test_trace_writer_drop_bound():
    tw = TraceWriter(max_events=3)  # metadata + one B/E pair fills it
    tw.complete("engine", "chunk", 0.01)
    tw.complete("engine", "chunk", 0.01)  # dropped pairwise
    tw.instant("engine", "x")  # dropped
    assert tw.dropped == 3
    _validate_trace(tw.events)


# ---- recorder + engine bit-exactness -------------------------------------


def test_obs_on_bit_exact_vs_fused():
    """The telemetry contract: a recorded chunked run retires exactly
    what the fused run() retires; `--obs off` IS the fused path (the
    engine's obs attribute defaults to None)."""
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=16)
    assert ref.obs is None  # off = no recorder anywhere near the engine
    ref.run()

    rec = Recorder("full")
    eng = Engine(cfg, tr, chunk_steps=16)
    rec.attach(eng)
    eng.run_chunked()

    assert np.array_equal(ref.cycles, eng.cycles)
    for k in ref.counters:
        assert np.array_equal(ref.counters[k], eng.counters[k]), k
    # every committed chunk landed in the ring, deltas sum to the totals
    s = rec.store.summary()
    assert s["chunks"] == len(rec.store)
    assert s["total_instructions"] == int(
        ref.counters["instructions"].sum()
    )
    _validate_trace(rec.trace.events)
    spans = [e for e in rec.trace.events if e["ph"] == "B"]
    assert len(spans) == s["chunks"]
    assert all("dispatch_ms" in e["args"] for e in spans)


def test_recorder_levels_and_finalize(tmp_path):
    with pytest.raises(ValueError):
        Recorder("verbose")
    basic = Recorder("basic")
    assert basic.enabled and not basic.tracing and basic.trace is None
    basic.supervisor_event("checkpoint", "noop at basic")  # must not throw

    mp, tp = str(tmp_path / "m.jsonl"), str(tmp_path / "t.json")
    rec = Recorder("full", metrics_path=mp, trace_path=tp)
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    rec.attach(eng)
    eng.run_chunked()
    written = rec.finalize()
    assert written["metrics"][0] == mp and written["trace"][0] == tp
    assert rec.finalize() is written  # idempotent
    _validate_trace(json.load(open(tp))["traceEvents"])
    assert all(json.loads(ln)["label"] == "engine" for ln in open(mp))


def test_supervisor_events_reach_trace(tmp_path):
    from primesim_tpu.sim.supervisor import RunSupervisor

    rec = Recorder("full")
    eng = Engine(_cfg(), _trace(), chunk_steps=16)
    rec.attach(eng)
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path / "snap"),
        checkpoint_every_chunks=1, handle_signals=False, obs=rec,
    )
    sup.run()
    assert sup.checkpoints_written >= 1
    sup_events = [
        e for e in rec.trace.events
        if e["ph"] == "i" and e.get("args", {}).get("msg")
    ]
    kinds = {e["name"] for e in sup_events}
    assert "checkpoint" in kinds
    _validate_trace(rec.trace.events)


# ---- serve surface -------------------------------------------------------


def _served_sched(tmp_path, obs=None):
    d = str(tmp_path / "srv")
    sched = Scheduler(
        _cfg(), JobJournal(d), d, buckets=((2, 1),), chunk_steps=16,
        max_queue=16, obs=obs,
    )
    jobs = [Job(job_id=f"j{i:06d}", synth=SMALL_SYNTH.format(i))
            for i in range(3)]
    for j in jobs:
        sched.submit(j)
    n = 0
    while not all(j.terminal for j in jobs):
        sched.tick()
        n += 1
        assert n < 5000
    return sched, jobs


def test_prometheus_text(tmp_path):
    sched, jobs = _served_sched(tmp_path)
    text = render_prometheus(sched, journal=sched.journal,
                             recovered={"jobs_replayed": 0,
                                        "jobs_requeued": 0})
    assert all(j.state == "DONE" for j in jobs)
    # required families (acceptance criteria: queue depth, job states,
    # latency histogram)
    for family in (
        "primetpu_queue_depth",
        'primetpu_jobs{state="DONE"} 3',
        "primetpu_job_latency_seconds_bucket",
        'primetpu_job_latency_seconds_bucket{le="+Inf"} 3',
        "primetpu_job_latency_seconds_count 3",
        "primetpu_jobs_completed_total 3",
        "primetpu_journal_fsync_seconds_bucket",
        "primetpu_slots_total 2",
        "primetpu_last_dispatch_age_seconds",
    ):
        assert family in text, family
    # text-format sanity: every non-comment line is `name[{labels}] value`
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE "))
            continue
        name_part, _, value = line.rpartition(" ")
        assert name_part and value
        float(value)  # parses as a number
    # histogram buckets are cumulative (monotone non-decreasing)
    buckets = [
        float(ln.rpartition(" ")[2])
        for ln in text.splitlines()
        if ln.startswith("primetpu_job_latency_seconds_bucket")
    ]
    assert buckets == sorted(buckets)


def test_scheduler_serve_events_in_trace(tmp_path):
    rec = Recorder("full")
    sched, jobs = _served_sched(tmp_path, obs=rec)
    kinds = {e["name"] for e in rec.trace.events if e["ph"] == "i"}
    assert {"admit", "dispatch", "retire"} <= kinds
    # fleet chunk spans carry the per-bucket label
    names = {e["args"]["name"] for e in rec.trace.events
             if e["ph"] == "M"}
    assert "bucket1p" in names
    # journal fsyncs landed as spans once the server wires journal.obs
    sched.journal.obs = rec
    sched.journal.note("post-wire fsync")
    assert any(
        e["ph"] == "B" and e["name"] == "fsync"
        for e in rec.trace.events
    )
    _validate_trace(rec.trace.events)


def test_journal_fsync_histogram(tmp_path):
    j = JobJournal(str(tmp_path / "jj"))
    before = j.fsync_hist.count
    j.note("one")
    j.note("two")
    assert j.fsync_hist.count == before + 2
    assert j.fsync_hist.sum > 0


def test_metrics_and_health_verbs(tmp_path):
    """The daemon surface, exercised in-process (the sighup-test
    pattern): `metrics` returns parseable Prometheus text, `health`
    carries recovery + journal + last-dispatch info."""
    from primesim_tpu.serve.server import PrimeServer

    server = PrimeServer(
        _cfg(), state_dir=str(tmp_path / "srv"), buckets=((2, 1),),
        chunk_steps=16,
    )
    job = Job(job_id="", synth=SMALL_SYNTH.format(7))
    job.job_id = server.sched.next_job_id()
    server.sched.submit(job)
    n = 0
    while not job.terminal:
        server.sched.tick()
        n += 1
        assert n < 5000

    out = server._handle({"verb": "metrics"})
    assert out["ok"] and out["content_type"].startswith("text/plain")
    assert "primetpu_queue_depth" in out["text"]
    assert 'primetpu_jobs{state="DONE"} 1' in out["text"]
    assert "primetpu_journal_fsync_seconds_count" in out["text"]

    h = server._handle({"verb": "health"})
    assert h["ok"]
    assert h["recovered"]["jobs_replayed"] == 0
    assert h["journal"]["appends"] == server.journal.appended > 0
    assert h["last_dispatch_t"] is not None
    assert h["last_dispatch_age_s"] >= 0


# ---- report TIMELINE -----------------------------------------------------


def test_report_timeline_section():
    from primesim_tpu.stats.report import render_report

    cfg, tr = _cfg(), _trace()
    rec = Recorder("basic")
    eng = Engine(cfg, tr, chunk_steps=16)
    rec.attach(eng)
    eng.run_chunked()
    with_tl = render_report(cfg, eng.counters, eng.cycles, wall_s=0.5,
                            timeline=rec.timeline_summary())
    assert "TIMELINE" in with_tl
    assert "peak chunk MIPS" in with_tl
    assert "slowest chunk" in with_tl
    without = render_report(cfg, eng.counters, eng.cycles, wall_s=0.5)
    assert "TIMELINE" not in without  # obs off leaves the report alone
