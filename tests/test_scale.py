"""Scale-ladder enablers (BASELINE rungs 4-5, VERDICT r3 item #7).

- PTPU v4 line-addressed traces: addr = cache-line index (2^31 lines =
  128 GiB at 64B lines, 64x the byte-addressed range; larger captured
  spaces still alias under the 31-bit mask). Both engines normalize
  ingest to line granularity, so a byte trace and its line-converted twin
  simulate identically; round-trips through the binary format preserve
  the flag and the capture line size.
- Chunked sharer reductions (cfg.sharer_chunk_words): the [C, C]
  invalidation/back-invalidation expansions become a lax.scan over K-word
  blocks with [C, 32K] temporaries — bit-exact vs both the dense engine
  path and the golden model.
- 4096-core step: compiles and runs with chunking enabled.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, EV_ST, Trace, from_event_lists

from test_parity import assert_parity
from test_parity_scale import scale_machine


# ------------------------------------------------------- v4 line addressing


def test_v4_roundtrip_preserves_line_flag(tmp_path):
    tr = from_event_lists(
        [[(EV_LD, 4, 123), (EV_ST, 4, 2**31 - 1)], [(EV_LD, 4, 0)]],
        line_addressed=True,
    )
    p = str(tmp_path / "t.ptpu")
    tr.save(p)
    tr2 = Trace.load(p)
    assert tr2.line_addressed
    np.testing.assert_array_equal(tr2.events, tr.events)


def test_line_addressed_equals_byte_addressed():
    # the same workload expressed byte- and line-addressed must produce
    # IDENTICAL simulations through both engines
    cfg = MachineConfig(n_cores=4, n_banks=4, quantum=500)
    byte_tr = synth.false_sharing(4, n_mem_ops=40, seed=61)
    ev = byte_tr.line_events(cfg.line_bits)
    line_tr = Trace(ev, byte_tr.lengths, line_addressed=True)

    gb = GoldenSim(cfg, byte_tr)
    gb.run()
    gl = GoldenSim(cfg, line_tr)
    gl.run()
    np.testing.assert_array_equal(gb.cycles, gl.cycles)
    for k in gb.counters:
        np.testing.assert_array_equal(gb.counters[k], gl.counters[k])
    # and the engine agrees with golden on the line-addressed form
    assert_parity(cfg, line_tr)


def test_line_addressed_wide_addresses_simulate():
    # line indices beyond 2^25 (byte addresses beyond 2^31) — impossible
    # in byte addressing — must simulate fine
    wide = 1 << 30  # line index ~ byte address 2^36
    cfg = MachineConfig(n_cores=2, n_banks=2)
    tr = from_event_lists(
        [
            [(EV_LD, 4, wide), (EV_ST, 4, wide)],
            [(EV_LD, 4, wide + 1)],
        ],
        line_addressed=True,
    )
    assert_parity(cfg, tr)


def test_captured_traces_are_line_addressed(tmp_path):
    # the C++ frontend emits v4 line-granular traces
    import shutil
    import subprocess

    if shutil.which("gcc") is None:
        pytest.skip("no toolchain")
    import os

    from primesim_tpu.ingest.capture import capture_run

    frontend = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "primesim_tpu", "frontend",
    )
    binary = str(tmp_path / "ocean_like")
    subprocess.run(
        ["gcc", "-O2", "-fno-builtin", "-o", binary,
         os.path.join(frontend, "examples", "ocean_like.c"), "-lpthread"],
        check=True, capture_output=True,
    )
    tr = capture_run([binary, "2", "1", "2"], line=64)
    assert tr.line_addressed
    assert tr.line_bits == 6  # capture line size travels in the v4 flags
    # heap line indices exceed 2^25 — BYTE addressing would have had to
    # alias these into its 2 GiB window; line addressing holds them
    mem = (tr.events[:, :, 0] == EV_LD) | (tr.events[:, :, 0] == EV_ST)
    assert tr.events[:, :, 2][mem].max() > (1 << 25)
    # line-size mismatch is rejected, not silently misinterpreted
    from primesim_tpu.config.machine import CacheConfig, MachineConfig

    bad_cfg = MachineConfig(
        n_cores=tr.n_cores, n_banks=2,
        l1=CacheConfig(size=1024, ways=2, line=32, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=32, latency=10),
    )
    with pytest.raises(ValueError, match="line"):
        tr.line_events(bad_cfg.line_bits)


# ------------------------------------------------- chunked sharer reductions


@pytest.mark.parametrize("chunk", [1, 2])
def test_parity_chunked_sharers_64core(chunk):
    # NW=2 at 64 cores; K=1 and K=2 cover multi-block and single-block
    cfg = scale_machine(64, 8, 8, sharer_chunk_words=chunk)
    assert_parity(
        cfg, synth.readers_writer(64, n_rounds=2, block_lines=4, seed=62),
        chunk_steps=64,
    )


def test_parity_chunked_sharers_sync_and_contention():
    cfg = scale_machine(
        64, 8, 8, sharer_chunk_words=2,
        noc=NocConfig(mesh_x=8, mesh_y=8, contention=True, contention_lat=2),
    )
    assert_parity(
        cfg, synth.barrier_phases(64, n_phases=2, work_per_phase=6, seed=63),
        chunk_steps=64,
    )


def test_4096core_step_runs_chunked():
    # BASELINE rung 4 scale: one chunk of steps compiles and runs with
    # bounded memory ([C, 64] temporaries instead of [C, C] = 16M)
    import jax.numpy as jnp

    from primesim_tpu.sim.engine import run_chunk
    from primesim_tpu.sim.state import init_state

    C = 4096
    cfg = MachineConfig(
        n_cores=C,
        n_banks=64,
        core=__import__("primesim_tpu.config.machine", fromlist=["CoreConfig"])
        .CoreConfig(cpi_pattern=(1, 1, 3, 3)),
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=64, latency=12),
        noc=NocConfig(mesh_x=8, mesh_y=8),
        quantum=1000,
        sharer_chunk_words=8,  # NW=128 -> 16 blocks
    )
    tr = synth.false_sharing(C, n_mem_ops=6, n_hot_lines=2, seed=64)
    events = jnp.asarray(tr.line_events(cfg.line_bits))
    st = run_chunk(cfg, 8, events, init_state(cfg), has_sync=False)
    assert int(st.step) == 8
    assert int(jnp.sum(st.counters)) > 0  # work actually happened


def test_16384core_step_runs_coarse():
    # BASELINE rung 5 scale (VERDICT r4 #5): with the full-map vector this
    # machine's sharer array alone is 256 GiB — the coarse vector (G=64,
    # 256 group bits) plus group-table reductions make the 16384-core step
    # executable on ONE chip. Small caches keep the CI footprint modest;
    # the shipped configs/rung5_16384core_wafer.json carries the full
    # geometry with the same sharer_group.
    import jax.numpy as jnp

    from primesim_tpu.config.machine import CoreConfig
    from primesim_tpu.sim.engine import run_chunk
    from primesim_tpu.sim.state import init_state

    C = 16384
    cfg = MachineConfig(
        n_cores=C,
        n_banks=256,
        core=CoreConfig(o3_overlap_256=128),
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=64, latency=16),
        noc=NocConfig(mesh_x=16, mesh_y=16),
        quantum=1000,
        sharer_group=64,
    )
    assert cfg.n_sharer_words == 8  # 256 groups, not 16384 bits
    tr = synth.false_sharing(C, n_mem_ops=4, n_hot_lines=2, seed=65)
    events = jnp.asarray(tr.line_events(cfg.line_bits))
    st = run_chunk(cfg, 4, events, init_state(cfg), has_sync=False)
    assert int(st.step) == 4
    assert int(jnp.sum(st.counters)) > 0
