"""Hop-by-hop router NoC model (SURVEY.md §2 #6 [DRIVER], VERDICT r4 #2).

Covers: exact analytic equivalence when uncontended, hand-computed FIFO
queueing on a shared link, cross-step link-clock carry, golden-vs-engine
bit-exact parity (memory + sync paths, including with local runs and the
fused run_loop's on-device rebase), and the load-dependence property.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import (
    CacheConfig,
    MachineConfig,
    NocConfig,
    small_test_config,
)
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, EV_ST, from_event_lists

from test_parity import assert_parity


def rcfg(n=4, mesh_x=2, mesh_y=2, **kw):
    return small_test_config(
        n,
        noc=NocConfig(
            mesh_x=mesh_x, mesh_y=mesh_y, link_lat=1, router_lat=1,
            contention=True, contention_model="router",
        ),
        **kw,
    )


def test_uncontended_equals_analytic():
    # a single transaction must cost exactly the analytic latency: the
    # hop walk with empty queues IS hops*link + (hops+1)*router
    tr = from_event_lists([[(EV_LD, 4, 0)], [], [], []])
    g_r = GoldenSim(rcfg(), tr)
    g_r.run()
    g_0 = GoldenSim(
        small_test_config(4, noc=NocConfig(mesh_x=2, mesh_y=2)), tr
    )
    g_0.run()
    np.testing.assert_array_equal(g_r.cycles, g_0.cycles)
    assert g_r.counters["noc_contention_cycles"].sum() == 0


def test_shared_link_fifo_queues():
    # 1x4 mesh: core 0 (tile 0) -> bank 2, core 1 (tile 1) -> bank 3.
    # Both requests cross the eastward link out of tile 1; core 1 has the
    # larger (clock, core) key, so it queues exactly link_lat behind core
    # 0's nominal arrival there.
    cfg = rcfg(4, mesh_x=4, mesh_y=1, n_banks=4)
    tr = from_event_lists([[(EV_LD, 4, 2 * 64)], [(EV_LD, 4, 3 * 64)], [], []])
    g = GoldenSim(cfg, tr)
    g.run()
    np.testing.assert_array_equal(
        g.counters["noc_contention_cycles"][:2], [0, 1]
    )
    # the touched links' clocks advanced to their last departures
    assert (g.link_free != 0).any()


def test_link_clock_carries_across_steps():
    # same shared-link pair twice: the second round's packets queue
    # behind the FIRST round's link departures (cross-step state), so
    # round 2 charges more than a fresh round-1-only run
    cfg = rcfg(4, mesh_x=4, mesh_y=1, n_banks=4)
    one = from_event_lists(
        [[(EV_LD, 4, 2 * 64)], [(EV_LD, 4, 3 * 64)], [], []]
    )
    two = from_event_lists(
        [
            [(EV_LD, 4, 2 * 64), (EV_LD, 4, 6 * 64)],
            [(EV_LD, 4, 3 * 64), (EV_LD, 4, 7 * 64)],
            [],
            [],
        ]
    )
    g1 = GoldenSim(cfg, one)
    g1.run()
    g2 = GoldenSim(cfg, two)
    g2.run()
    assert (
        g2.counters["noc_contention_cycles"].sum()
        > g1.counters["noc_contention_cycles"].sum()
    )


@pytest.mark.parametrize(
    "gen",
    ["false_sharing", "uniform_random", "lock_contention", "barrier_phases"],
)
def test_parity_router(gen):
    cfg = rcfg(4, n_banks=4, quantum=300)
    tr = {
        "false_sharing": lambda: synth.false_sharing(4, n_mem_ops=40, seed=61),
        "uniform_random": lambda: synth.uniform_random(4, n_mem_ops=50, seed=62),
        "lock_contention": lambda: synth.lock_contention(4, n_critical=8, seed=63),
        "barrier_phases": lambda: synth.barrier_phases(4, n_phases=2, seed=64),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=50)


def test_parity_router_16core_hot_path():
    # many cores streaming through the same mesh column: deep per-link
    # FIFOs and multi-step queue carry; engine must stay bit-exact
    cfg = MachineConfig(
        n_cores=16, n_banks=16,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=8192, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=4, mesh_y=4, contention=True,
                      contention_model="router"),
        quantum=400,
    )
    evs = [
        [(EV_LD, 4, ((c + i) % 16) * 64) for i in range(8)] for c in range(16)
    ]
    assert_parity(cfg, from_event_lists(evs), chunk_steps=50)


def test_parity_router_with_local_runs_and_o3():
    # rung-3-shaped machine features together: router + local runs + O3
    # overlap + heterogeneous CPI; exercises the fused run_loop path with
    # its on-device link_free rebase
    from primesim_tpu.config.machine import CoreConfig

    cfg = small_test_config(
        8, n_banks=8, quantum=500, local_run_len=4,
        core=CoreConfig(cpi_pattern=(1, 2), o3_overlap_256=64),
        noc=NocConfig(mesh_x=4, mesh_y=2, contention=True,
                      contention_model="router"),
    )
    evs = []
    rng = np.random.default_rng(5)
    for c in range(8):
        core = []
        for i in range(30):
            line = int(rng.integers(0, 24))
            t = EV_ST if rng.random() < 0.4 else EV_LD
            core.append((t, 2, line * 64))
        evs.append(core)
    assert_parity(cfg, from_event_lists(evs), chunk_steps=16)


def test_router_is_load_dependent():
    # rung-3 property: hot-bank streaming takes longer (and reports
    # queueing cycles) with the router than without contention
    evs = [
        [(EV_LD, 4, (4 * ((i + 2 * c) % 16)) * 64) for i in range(12)]
        for c in range(8)
    ]
    tr = from_event_lists(evs)
    on = GoldenSim(rcfg(8, n_banks=4), tr)
    on.run()
    off = GoldenSim(
        small_test_config(
            8, n_banks=4, noc=NocConfig(mesh_x=2, mesh_y=2)
        ),
        tr,
    )
    off.run()
    assert on.counters["noc_contention_cycles"].sum() > 0
    assert on.cycles.max() > off.cycles.max()


def test_engine_link_free_matches_golden():
    # short run, no rebase: the engine's epoch-relative link clocks must
    # equal the golden's absolute ones exactly
    import jax.numpy as jnp

    from primesim_tpu.sim.engine import Engine

    cfg = rcfg(4, n_banks=4)
    tr = from_event_lists(
        [[(EV_LD, 4, 2 * 64)], [(EV_LD, 4, 3 * 64)], [], []]
    )
    g = GoldenSim(cfg, tr)
    g.run()
    e = Engine(cfg, tr, chunk_steps=8)
    e.run()
    np.testing.assert_array_equal(
        np.asarray(e.state.link_free) + int(e.cycle_base), g.link_free
    )
