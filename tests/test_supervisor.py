"""Resilient execution layer (DESIGN.md §10): supervised runs, crash
recovery, retry/degradation, invariant guard, fleet fault isolation.

The crash-recovery tests are deterministic: the supervisor's `on_chunk`
callback fires after every committed chunk, so `os.kill(os.getpid(),
SIGTERM)` from inside it lands the signal at an exact chunk boundary —
no sleeps, no races — and the resumed run must be bit-exact with an
uninterrupted one (cycles, every counter, full machine state).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from primesim_tpu.config.machine import MachineConfig, small_test_config
from primesim_tpu.sim.checkpoint import (
    CheckpointCorrupt,
    atomic_save_npz,
    load_verified_npz,
)
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.supervisor import (
    GuardViolation,
    Preempted,
    RunSupervisor,
    SnapshotStore,
    build_fleet_isolated,
    classify_failure,
)
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import Trace, TraceError, validate_sync

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg():
    return small_test_config(8, n_banks=4, quantum=200)


def _trace(seed=41):
    return synth.fft_like(8, n_phases=2, points_per_core=12, seed=seed)


def _full_state_equal(a, b):
    for k in a._fields:
        va, vb = getattr(a, k), getattr(b, k)
        if hasattr(va, "_fields"):
            _full_state_equal(va, vb)
            continue
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=k)


def _same_results(eng, ref):
    np.testing.assert_array_equal(eng.cycles, ref.cycles)
    rc = ref.counters
    for k, v in eng.counters.items():
        np.testing.assert_array_equal(v, rc[k], err_msg=k)


def _kill_at(chunk):
    def on_chunk(sup):
        if sup.committed == chunk:
            os.kill(os.getpid(), signal.SIGTERM)

    return on_chunk


# ---- failure classification ----------------------------------------------


def test_classify_failure():
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: oom")) == "oom"
    assert classify_failure(RuntimeError("Out of memory allocating")) == "oom"
    assert classify_failure(RuntimeError("UNAVAILABLE: socket")) == "transient"
    assert classify_failure(RuntimeError("DEADLINE_EXCEEDED")) == "transient"
    assert classify_failure(RuntimeError("something else")) is None
    # deliberate errors are never retried, whatever their text says
    assert classify_failure(ValueError("UNAVAILABLE")) is None
    assert classify_failure(AssertionError("RESOURCE_EXHAUSTED")) is None
    assert classify_failure(KeyboardInterrupt()) is None


# ---- atomic writer + CRC manifest ----------------------------------------


def test_crc_manifest_detects_bit_flip(tmp_path):
    p = str(tmp_path / "c.npz")
    atomic_save_npz(p, a=np.arange(16, dtype=np.int32), b=np.ones(3))
    z = load_verified_npz(p)
    np.testing.assert_array_equal(z["a"], np.arange(16, dtype=np.int32))

    # tamper with one array but keep the stale manifest
    with np.load(p) as f:
        data = {k: f[k] for k in f.files}
    data["a"] = data["a"].copy()
    data["a"][3] ^= 1
    np.savez_compressed(p, **data)
    with pytest.raises(CheckpointCorrupt, match="CRC32"):
        load_verified_npz(p)


def test_truncated_snapshot_is_corrupt_not_mismatch(tmp_path):
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    eng.run_steps(16)
    p = str(tmp_path / "c.npz")
    eng.save_checkpoint(p)
    blob = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(CheckpointCorrupt):
        Engine(cfg, tr, chunk_steps=16).load_checkpoint(p)
    # a missing file stays FileNotFoundError ("no snapshot" != "bad one")
    with pytest.raises(FileNotFoundError):
        load_verified_npz(str(tmp_path / "nope.npz"))


# ---- snapshot rotation ----------------------------------------------------


def test_snapshot_store_rotation_and_sequence(tmp_path):
    store = SnapshotStore(str(tmp_path), keep=3)

    def save(path):
        atomic_save_npz(path, x=np.zeros(1))

    paths = [store.save(save) for _ in range(5)]
    assert [os.path.basename(p) for p in paths] == [
        f"ckpt-{i:08d}.npz" for i in range(1, 6)
    ]
    kept = store.snapshots()
    assert [os.path.basename(p) for p in kept] == [
        "ckpt-00000005.npz", "ckpt-00000004.npz", "ckpt-00000003.npz",
    ]
    # sequence numbers keep growing past survivors — newest is a pure
    # filename sort, never an mtime comparison
    assert os.path.basename(store.save(save)) == "ckpt-00000006.npz"


# ---- preempt + resume, bit-exact, all three engines ----------------------


@pytest.mark.parametrize("seed", [0, 1])
def test_solo_preempt_resume_bit_exact(tmp_path, seed):
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    kill_chunk = 1 + int(np.random.default_rng(seed).integers(0, 3))
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path), checkpoint_every_chunks=1,
        guard="fail", on_chunk=_kill_at(kill_chunk),
    )
    with pytest.raises(Preempted) as ei:
        sup.run()
    assert ei.value.checkpoint is not None
    assert os.path.exists(ei.value.checkpoint)
    assert not eng.done()  # killed mid-run, not at the end

    eng2 = Engine(cfg, tr, chunk_steps=16)
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path), guard="fail")
    assert sup2.resume() == ei.value.checkpoint
    sup2.run()
    _same_results(eng2, ref)
    _full_state_equal(eng2.state, ref.state)


def test_stream_preempt_resume_bit_exact(tmp_path):
    from primesim_tpu.ingest.stream import StreamEngine

    cfg = small_test_config(8, n_banks=4, quantum=200)
    tr = synth.false_sharing(8, n_mem_ops=40, seed=44)
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    eng = StreamEngine(cfg, tr, window_events=8)
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path), checkpoint_every_chunks=1,
        on_chunk=_kill_at(2),
    )
    with pytest.raises(Preempted):
        sup.run()
    assert not eng.done()

    eng2 = StreamEngine(cfg, tr, window_events=8)
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path))
    assert sup2.resume() is not None
    sup2.run()
    _same_results(eng2, ref)


def test_fleet_preempt_resume_bit_exact(tmp_path):
    from primesim_tpu.sim.fleet import FleetEngine

    cfg = _cfg()
    traces = [_trace(45), synth.false_sharing(8, n_mem_ops=40, seed=47)]
    overrides = [{}, {"llc_lat": 25}]

    ref = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    ref.run()

    eng = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path), checkpoint_every_chunks=1,
        on_chunk=_kill_at(2),
    )
    with pytest.raises(Preempted):
        sup.run()
    assert not eng.done()

    eng2 = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    sup2 = RunSupervisor(eng2, snapshot_dir=str(tmp_path))
    assert sup2.resume() is not None
    sup2.run()
    # cycles + every counter match the fused uninterrupted run (the
    # fused loop freezes finished elements' step bookkeeping while the
    # chunked path ticks it, so full-state equality is asserted against
    # an uninterrupted run of the SAME cadence below)
    _same_results(eng2, ref)

    eng3 = FleetEngine(cfg, traces, overrides, chunk_steps=16)
    RunSupervisor(eng3).run()
    _same_results(eng3, ref)
    _full_state_equal(eng2.state, eng3.state)


def test_preempt_without_snapshot_dir():
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(eng, on_chunk=_kill_at(1))
    with pytest.raises(Preempted) as ei:
        sup.run()
    assert ei.value.checkpoint is None


def test_second_signal_raises_keyboard_interrupt(tmp_path):
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)

    def double_kill(sup):
        if sup.committed == 1:
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):  # let the first delivery run the handler
                pass
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):
                pass

    sup = RunSupervisor(eng, on_chunk=double_kill)
    with pytest.raises(KeyboardInterrupt):
        sup.run()


# ---- corrupt-snapshot fallback -------------------------------------------


def _run_and_snapshot(tmp_path, kill_chunk=3):
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(
        eng, snapshot_dir=str(tmp_path), checkpoint_every_chunks=1,
        on_chunk=_kill_at(kill_chunk),
    )
    with pytest.raises(Preempted):
        sup.run()
    return cfg, tr


def test_resume_falls_back_past_corrupt_newest(tmp_path):
    cfg, tr = _run_and_snapshot(tmp_path)
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    store = SnapshotStore(str(tmp_path))
    snaps = store.snapshots()
    assert len(snaps) >= 2
    blob = open(snaps[0], "rb").read()
    with open(snaps[0], "wb") as f:
        f.write(blob[: len(blob) // 3])  # torn newest

    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(eng, snapshot_dir=str(tmp_path))
    assert sup.resume() == snaps[1]  # fell back to next-newest valid
    assert any("resume-skip" in ln for ln in sup.log_lines())
    sup.run()
    _same_results(eng, ref)


def test_resume_all_corrupt_raises(tmp_path):
    cfg, tr = _run_and_snapshot(tmp_path)
    for p in SnapshotStore(str(tmp_path)).snapshots():
        with open(p, "wb") as f:
            f.write(b"not an npz")
    sup = RunSupervisor(Engine(cfg, tr, chunk_steps=16),
                        snapshot_dir=str(tmp_path))
    with pytest.raises(CheckpointCorrupt, match="all .* corrupt"):
        sup.resume()


def test_resume_empty_dir_starts_fresh(tmp_path):
    cfg, tr = _cfg(), _trace()
    sup = RunSupervisor(Engine(cfg, tr, chunk_steps=16),
                        snapshot_dir=str(tmp_path))
    assert sup.resume() is None


def test_resume_wrong_run_is_hard_error(tmp_path):
    # a healthy snapshot of a DIFFERENT run must not be skipped like a
    # corrupt one — silently resuming the wrong run is worse than dying
    cfg, tr = _run_and_snapshot(tmp_path)
    other = Engine(cfg, synth.fft_like(8, n_phases=2, points_per_core=12,
                                       seed=99), chunk_steps=16)
    sup = RunSupervisor(other, snapshot_dir=str(tmp_path))
    with pytest.raises(ValueError, match="trace does not match"):
        sup.resume()


# ---- retry / degradation -------------------------------------------------


def test_oom_halves_chunk_and_stays_bit_exact(tmp_path):
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    eng = Engine(cfg, tr, chunk_steps=16)
    orig = eng.run_steps
    fails = {"left": 2}

    def flaky(n):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return orig(n)

    eng.run_steps = flaky
    sup = RunSupervisor(eng, backoff_s=0.01)
    sup.run()
    assert eng.chunk_steps == 4  # 16 -> 8 -> 4
    assert sup.retries == 2
    assert any("degrade" in ln for ln in sup.log_lines())
    _same_results(eng, ref)  # halving never changes results


def test_transient_retry_with_backoff_then_success(tmp_path):
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    eng = Engine(cfg, tr, chunk_steps=16)
    orig = eng.run_steps
    fails = {"left": 3}

    def flaky(n):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("UNAVAILABLE: connection to device lost")
        return orig(n)

    eng.run_steps = flaky
    sup = RunSupervisor(eng, backoff_s=0.001)
    sup.run()
    assert sup.retries == 3
    assert eng.chunk_steps == 16  # transient failures don't shrink chunks
    _same_results(eng, ref)


def test_retry_exhaustion_raises_original(tmp_path):
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)

    def always_down(n):
        raise RuntimeError("UNAVAILABLE: device gone")

    eng.run_steps = always_down
    sup = RunSupervisor(eng, max_retries=2, backoff_s=0.001)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        sup.run()
    assert sup.retries == 2
    assert any("give-up" in ln for ln in sup.log_lines())


def test_permanent_error_is_not_retried():
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)

    def broken(n):
        raise ValueError("deliberate config error")

    eng.run_steps = broken
    sup = RunSupervisor(eng, backoff_s=0.001)
    with pytest.raises(ValueError, match="deliberate"):
        sup.run()
    assert sup.retries == 0


def test_failed_dispatch_rolls_back_host_state(tmp_path):
    # a dispatch that dies AFTER mutating host accumulators must not
    # double-count when the retry succeeds — covered implicitly by the
    # bit-exactness asserts above, explicitly here: fail on the SECOND
    # chunk, after real host state exists
    cfg, tr = _cfg(), _trace()
    ref = Engine(cfg, tr, chunk_steps=16)
    ref.run()

    eng = Engine(cfg, tr, chunk_steps=16)
    orig = eng.run_steps
    state = {"calls": 0}

    def flaky(n):
        state["calls"] += 1
        if state["calls"] == 2:
            orig(n)  # mutates host counters/steps_run ...
            raise RuntimeError("UNAVAILABLE: died after the work")
        return orig(n)

    eng.run_steps = flaky
    sup = RunSupervisor(eng, backoff_s=0.001)
    sup.run()
    _same_results(eng, ref)


# ---- invariant guard ------------------------------------------------------


def _corrupt_at(eng, chunk):
    def on_chunk(sup):
        if sup.committed == chunk:
            st = eng.state
            eng.state = st._replace(lock_holder=st.lock_holder.at[0].set(99))

    return on_chunk


def test_guard_fail_stops_on_corrupted_state():
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(eng, guard="fail", on_chunk=_corrupt_at(eng, 2))
    with pytest.raises(GuardViolation, match="lock_holder"):
        sup.run()


def test_guard_warn_logs_and_continues():
    cfg, tr = _cfg(), _trace()  # lock-free trace: corruption is inert
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(eng, guard="warn", on_chunk=_corrupt_at(eng, 2))
    sup.run()
    assert eng.done()
    assert sup.guard_warnings >= 1
    assert any("guard-warn" in ln for ln in sup.log_lines())


def test_guard_off_ignores_corruption():
    cfg, tr = _cfg(), _trace()
    eng = Engine(cfg, tr, chunk_steps=16)
    sup = RunSupervisor(eng, guard="off", on_chunk=_corrupt_at(eng, 2))
    sup.run()
    assert sup.guard_warnings == 0


def test_guard_fail_passes_clean_runs():
    # no false positives on healthy runs, including sync-heavy ones
    # (barrier-frozen cores legally lag quantum_end; the live mask must
    # exclude them or the skew check misfires)
    cfg = _cfg()
    for tr in (_trace(), synth.barrier_phases(8, n_phases=3, seed=5),
               synth.lock_contention(8, n_critical=8, seed=42)):
        eng = Engine(cfg, tr, chunk_steps=16)
        RunSupervisor(eng, guard="fail").run()
        assert eng.done()


# ---- typed trace errors (S2) ---------------------------------------------


def test_trace_error_carries_core_and_offset():
    tr = _trace()
    ev = tr.events.copy()
    ev[2, 3, 0] = 99  # invalid event type at core 2, offset 3
    with pytest.raises(TraceError) as ei:
        Trace(ev, tr.lengths)
    e = ei.value
    assert (e.core, e.offset) == (2, 3)
    assert "core 2" in str(e) and "event 3" in str(e)
    assert e.location() == {"core": 2, "offset": 3}


def test_trace_error_barrier_ids_located():
    tr = synth.barrier_phases(4, n_phases=2, seed=7)
    with pytest.raises(TraceError) as ei:
        validate_sync(tr, barrier_slots=1)  # ids alternate over 2 slots
    e = ei.value
    assert e.core is not None and e.offset is not None
    assert "barrier" in e.reason


def test_trace_error_load_path_attached(tmp_path):
    bad = str(tmp_path / "bad.ptpu")
    with open(bad, "wb") as f:
        f.write(b"garbage garbage garbage")
    with pytest.raises(TraceError) as ei:
        Trace.load(bad)
    assert ei.value.path == bad
    assert bad in str(ei.value)


# ---- fleet fault isolation -----------------------------------------------


def test_build_fleet_isolated_quarantines_and_matches_solo():
    cfg = _cfg()
    good0, good2 = _trace(45), synth.false_sharing(8, n_mem_ops=40, seed=47)

    def broken_loader():
        raise TraceError("unreadable element", path="x.ptpu", core=2, offset=5)

    fleet, quarantined = build_fleet_isolated(
        cfg, [good0, broken_loader, good2], chunk_steps=16
    )
    assert [i for i, _ in quarantined] == [1]
    assert isinstance(quarantined[0][1], TraceError)
    assert fleet.element_ids == [0, 2]
    fleet.run()

    solo = Engine(cfg, good0, chunk_steps=16)
    solo.run()
    np.testing.assert_array_equal(fleet.cycles[0], solo.cycles)
    fc, sc = fleet.counters, solo.counters
    for k in sc:
        np.testing.assert_array_equal(fc[k][0], sc[k], err_msg=k)


def test_build_fleet_isolated_bad_override_quarantined():
    cfg = _cfg()
    fleet, quarantined = build_fleet_isolated(
        cfg, [_trace(), _trace()], [{}, {"bogus_knob": 3}], chunk_steps=16
    )
    assert [i for i, _ in quarantined] == [1]
    assert fleet.element_ids == [0]


def test_build_fleet_isolated_nothing_survives():
    def boom():
        raise OSError("disk on fire")

    fleet, quarantined = build_fleet_isolated(_cfg(), [boom, boom])
    assert fleet is None and len(quarantined) == 2


# ---- CLI surface ----------------------------------------------------------


def _write_cfg(tmp_path):
    p = str(tmp_path / "m.json")
    with open(p, "w") as f:
        f.write(MachineConfig(n_cores=8, n_banks=8).to_json())
    return p


def _last_json_lines(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(ln) for ln in out if ln.startswith("{")]


def test_cli_supervised_run_and_resume_bit_exact(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    spec = "fft_like:n_phases=2,points_per_core=12"
    ckdir = str(tmp_path / "ck")

    rc = main(["run", cfg, "--synth", spec, "--chunk-steps", "16"])
    assert rc == 0
    ref = _last_json_lines(capsys)[-1]["detail"]

    rpt = str(tmp_path / "r.txt")
    rc = main(["run", cfg, "--synth", spec, "--chunk-steps", "16",
               "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
               "--guard", "fail", "--report", rpt])
    assert rc == 0
    sup = _last_json_lines(capsys)[-1]["detail"]
    assert sup["supervised"] is True and sup["checkpoints_written"] >= 1
    assert sup["instructions"] == ref["instructions"]
    assert sup["max_core_cycles"] == ref["max_core_cycles"]
    assert "RESILIENCE" in open(rpt).read()

    # tear the newest snapshot; --resume must fall back and still finish
    # bit-exact with the uninterrupted run
    snaps = SnapshotStore(ckdir).snapshots()
    blob = open(snaps[0], "rb").read()
    with open(snaps[0], "wb") as f:
        f.write(blob[: len(blob) // 2])
    rc = main(["run", cfg, "--synth", spec, "--chunk-steps", "16",
               "--checkpoint-dir", ckdir, "--resume"])
    assert rc == 0
    res = _last_json_lines(capsys)[-1]["detail"]
    assert res["resumed_from"] == snaps[1]
    assert res["instructions"] == ref["instructions"]
    assert res["max_core_cycles"] == ref["max_core_cycles"]


def test_cli_resume_requires_checkpoint_dir(tmp_path):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    with pytest.raises(SystemExit):
        main(["run", cfg, "--synth", "fft_like", "--resume"])
    with pytest.raises(SystemExit):
        main(["run", cfg, "--synth", "fft_like", "--checkpoint-every", "2"])


def test_cli_sweep_quarantines_bad_element(tmp_path, capsys):
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    bad = str(tmp_path / "bad.ptpu")
    with open(bad, "wb") as f:
        f.write(b"definitely not a trace")

    rc = main(["sweep", cfg, "--trace", bad,
               "--synth", "false_sharing:n_mem_ops=20",
               "--chunk-steps", "16"])
    # the batch survives the bad element, and exit 3 flags the partial
    # outcome (healthy results emitted, casualties reported)
    assert rc == 3
    lines = _last_json_lines(capsys)
    quar = [l for l in lines if l["metric"] == "quarantined"]
    assert len(quar) == 1
    assert quar[0]["detail"]["fleet_index"] == 0
    assert quar[0]["detail"]["status"] == "quarantined"
    err = quar[0]["detail"]["error"]  # structured: type/location/detail
    assert set(err) >= {"type", "location", "detail"}
    assert "bad.ptpu" in err["detail"]
    agg = [l for l in lines if l["metric"] == "fleet_aggregate_MIPS"]
    assert agg and agg[0]["detail"]["quarantined"] == [0]
    elems = [l for l in lines if l["metric"] == "simulated_MIPS"]
    assert len(elems) == 1 and elems[0]["detail"]["fleet_index"] == 1

    # --strict turns the same input into a hard failure: exit 2 with one
    # structured JSON error line on stderr (the typed-error contract)
    rc = main(["sweep", cfg, "--trace", bad,
               "--synth", "false_sharing:n_mem_ops=20", "--strict"])
    assert rc == 2
    err_lines = [l for l in capsys.readouterr().err.splitlines()
                 if l.startswith("{")]
    assert err_lines
    err = json.loads(err_lines[-1])["error"]
    assert err["type"] == "TraceError" and "bad.ptpu" in err["detail"]


# ---- acceptance: real SIGTERM against a real process ---------------------


@pytest.mark.slow
def test_subprocess_sigterm_leaves_valid_checkpoint(tmp_path):
    """kill -TERM mid-run leaves a valid checkpoint (exit 75 =
    EX_TEMPFAIL) and --resume finishes bit-exact. Real process, real
    signal — the in-process tests above pin the boundary semantics;
    this one pins the wiring (handler installation, exit code, atomic
    files on a real crash-exit)."""
    from primesim_tpu.cli import main

    cfg = _write_cfg(tmp_path)
    spec = "fft_like:n_phases=6,points_per_core=96"
    ckdir = str(tmp_path / "ck")
    argv = ["run", cfg, "--synth", spec, "--chunk-steps", "8",
            "--checkpoint-dir", ckdir, "--checkpoint-every", "1"]
    code = (
        "import sys; from primesim_tpu.cli import main; "
        "sys.exit(main(%r))" % (argv,)
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        # wait for the first snapshot, then preempt
        import time

        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.isdir(ckdir) and SnapshotStore(ckdir).snapshots():
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is not None:
            pytest.skip("run finished before SIGTERM could land")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        proc.kill()
    assert rc == 75, proc.stderr.read().decode()[-2000:]
    snaps = SnapshotStore(ckdir).snapshots()
    assert snaps  # a valid snapshot survived the preemption

    # resume in-process and compare against an uninterrupted run
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert main(argv + ["--resume"]) == 0
    resumed = json.loads(
        [l for l in buf.getvalue().splitlines() if l.startswith("{")][-1]
    )["detail"]

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert main(["run", cfg, "--synth", spec, "--chunk-steps", "8"]) == 0
    ref = json.loads(
        [l for l in buf.getvalue().splitlines() if l.startswith("{")][-1]
    )["detail"]
    assert resumed["instructions"] == ref["instructions"]
    assert resumed["max_core_cycles"] == ref["max_core_cycles"]
