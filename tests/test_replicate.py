"""Tests for journal replication + fenced hot-standby failover
(serve/replicate.py, DESIGN.md §21): byte-identical follower chains,
compaction-aware catch-up, quorum policies, fencing epochs, the
`fsck --compare` checker, client failover rotation, and the @slow
subprocess acceptance — kill -9 of the primary PLUS deletion of its
state dir, with zero ACKed jobs lost.

Everything fast runs the real wire protocol against in-process
`ReplicaServer` threads on 127.0.0.1; only the acceptance test spawns
real daemons.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import pytest

from primesim_tpu.analysis.fsck import run_compare
from primesim_tpu.config.machine import small_test_config
from primesim_tpu.serve.journal import JobJournal, serve_compactor
from primesim_tpu.serve.replicate import (
    PrimaryFenced,
    ReplicaQuorumLost,
    ReplicaServer,
    ReplicationSink,
    Standby,
    pull_chain,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMALL_SYNTH = "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed={}"


def _accept_rec(i):
    from primesim_tpu.serve.jobs import Job

    job = Job(job_id=f"j{i}", synth=SMALL_SYNTH.format(i), client="c",
              idem=f"t{i}")
    return {"t": "accept", "job": job.accept_record()}


def _chain_bytes(d):
    """{segment filename: content} for every journal file in a dir."""
    out = {}
    for name in sorted(os.listdir(d)):
        if name.startswith("journal"):
            with open(os.path.join(d, name)) as f:
                out[name] = f.read()
    return out


def _replicated_journal(tmp_path, n_replicas=2, segment_records=4,
                        **sink_kw):
    replicas = [
        ReplicaServer(str(tmp_path / f"replica{i}"), "127.0.0.1:0")
        for i in range(n_replicas)
    ]
    targets = [r.start() for r in replicas]
    pdir = str(tmp_path / "primary")
    os.makedirs(pdir, exist_ok=True)
    j = JobJournal(pdir, segment_records=segment_records,
                   compactor=serve_compactor)
    sink = ReplicationSink(j, targets, **sink_kw)
    j.sink = sink
    sink.begin_epoch()
    return j, sink, replicas, targets, pdir


# ---- byte-identical replication ------------------------------------------


def test_replicated_chains_byte_identical_across_rolls(tmp_path):
    j, sink, replicas, _, pdir = _replicated_journal(tmp_path)
    for i in range(11):  # > 2 rolls at segment_records=4
        j.append({"t": "accept", "job_id": f"j{i}", "spec": {"n": i}})
    assert sink.quorum_ok()
    want = _chain_bytes(pdir)
    assert len(want) >= 3  # rolled at least twice
    for r in replicas:
        assert _chain_bytes(r.store.dir) == want
    sink.close()
    j.close()


def test_compaction_under_replication_resyncs_followers(tmp_path):
    j, sink, replicas, _, pdir = _replicated_journal(tmp_path)
    for i in range(9):
        j.append(_accept_rec(i))
        j.append({"t": "state", "job_id": f"j{i}", "state": "DONE"})
    j.compact()
    j.append(_accept_rec(99))
    assert j.compactions >= 1
    want = _chain_bytes(pdir)
    for r in replicas:
        assert _chain_bytes(r.store.dir) == want
        assert run_compare(pdir, r.store.dir).clean
    sink.close()
    j.close()


def test_pool_ledger_replicates_through_same_machinery(tmp_path):
    """The pool coordinator's ledger is the same JobJournal class, so
    pool-shaped records replicate byte-identically with zero extra
    wiring — the 'for free' claim in the module doc."""
    j, sink, replicas, _, pdir = _replicated_journal(tmp_path)
    j.append({"t": "unit", "unit_id": "u1", "spec": "s1"})
    j.append({"t": "lease", "unit_id": "u1", "worker": "w1", "epoch": 1})
    j.append({"t": "ack", "unit_id": "u1", "worker": "w1",
              "result": {"cycles": 42}})
    want = _chain_bytes(pdir)
    for r in replicas:
        assert _chain_bytes(r.store.dir) == want
    sink.close()
    j.close()


# ---- catch-up ------------------------------------------------------------


def test_follower_catches_up_across_two_rolls_chain_identical(tmp_path):
    """A follower that is DOWN while the primary rolls the active
    segment twice must, on rebirth, converge to a byte-identical chain
    via the segment-range resync — not just a compatible one."""
    # 3 replicas: the majority quorum (2) survives one follower's death
    j, sink, replicas, targets, pdir = _replicated_journal(
        tmp_path, n_replicas=3, segment_records=3
    )
    j.append({"t": "accept", "job_id": "j0", "spec": {}})
    replicas[0].die()
    time.sleep(0.05)
    for i in range(1, 9):  # rolls the active segment at least twice
        j.append({"t": "accept", "job_id": f"j{i}", "spec": {}})
    # quorum 2 of 3: the surviving followers kept the primary ACKing
    assert sink.quorum_ok()
    assert _chain_bytes(replicas[0].store.dir) != _chain_bytes(pdir)

    # rebirth over the SURVIVING directory, fresh port
    reborn = ReplicaServer(replicas[0].store.dir, "127.0.0.1:0")
    new_target = reborn.start()
    link = sink.links[0]
    link.target = new_target
    link.retry_at = 0.0
    link.blackout_until = 0.0
    sink.heartbeat()

    want = _chain_bytes(pdir)
    assert _chain_bytes(reborn.store.dir) == want
    for r in replicas[1:]:
        assert _chain_bytes(r.store.dir) == want
    assert sink.resyncs >= 1
    sink.close()
    j.close()


def test_recovered_replica_resyncs_once_per_append(tmp_path):
    """An append to a freshly recovered (needs_sync) replica costs ONE
    wholesale sync — the sync ships the active segment already holding
    the frame, so replaying the per-frame order would only bounce off
    the position check and buy a second full resync."""
    j, sink, replicas, targets, pdir = _replicated_journal(
        tmp_path, n_replicas=3
    )
    replicas[0].die()
    time.sleep(0.05)
    link = sink.links[0]
    link._drop()  # the failure detector's verdict, made deterministic
    j.append({"t": "accept", "job_id": "j0", "spec": {}})  # missed by r0
    reborn = ReplicaServer(replicas[0].store.dir, "127.0.0.1:0")
    link.target = reborn.start()
    link.retry_at = 0.0
    link.blackout_until = 0.0
    before = sink.resyncs
    j.append({"t": "accept", "job_id": "j1", "spec": {}})
    assert sink.resyncs == before + 1  # exactly one sync, counted as ack
    assert sink.quorum_ok()
    assert _chain_bytes(reborn.store.dir) == _chain_bytes(pdir)
    sink.close()
    j.close()


def test_follower_behind_base_resyncs_from_base(tmp_path):
    j, sink, replicas, targets, pdir = _replicated_journal(
        tmp_path, segment_records=3
    )
    replicas[0].die()
    time.sleep(0.05)
    for i in range(7):
        j.append(_accept_rec(i))
        j.append({"t": "state", "job_id": f"j{i}", "state": "DONE"})
    j.compact()  # the dead follower is now behind the BASE
    reborn = ReplicaServer(replicas[0].store.dir, "127.0.0.1:0")
    sink.links[0].target = reborn.start()
    sink.links[0].retry_at = 0.0
    sink.heartbeat()
    assert _chain_bytes(reborn.store.dir) == _chain_bytes(pdir)
    assert reborn.store.dir not in (None, pdir)
    sink.close()
    j.close()


# ---- quorum policies -----------------------------------------------------


def test_quorum_block_raises_replica_quorum_lost(tmp_path):
    pdir = str(tmp_path / "p")
    os.makedirs(pdir)
    j = JobJournal(pdir)
    # nobody listens on these targets: every ship misses quorum
    sink = ReplicationSink(j, [str(tmp_path / "void0.sock"),
                               str(tmp_path / "void1.sock")],
                           policy="block", retry_after_s=1.5)
    j.sink = sink
    sink.begin_epoch()
    assert not sink.quorum_ok()
    with pytest.raises(ReplicaQuorumLost) as ei:
        sink.check_admission()
    assert ei.value.retry_after_s == 1.5
    sink.close()
    j.close()


def test_quorum_degrade_acks_locally_and_counts(tmp_path):
    pdir = str(tmp_path / "p")
    os.makedirs(pdir)
    j = JobJournal(pdir)
    sink = ReplicationSink(j, [str(tmp_path / "void.sock")],
                           policy="degrade")
    j.sink = sink
    sink.begin_epoch()
    j.append({"t": "accept", "job_id": "j1", "spec": {}})
    sink.check_admission()  # degrade: does NOT raise
    assert sink.degraded_acks >= 2  # epoch frame + the append
    assert sink.quorum_losses >= 2
    assert not sink.quorum_ok()
    st = sink.status()
    assert st["policy"] == "degrade" and not st["quorum_ok"]
    sink.close()
    j.close()


def test_quorum_validation_rejects_out_of_range(tmp_path):
    pdir = str(tmp_path / "p")
    os.makedirs(pdir)
    j = JobJournal(pdir)
    with pytest.raises(ReplicaQuorumLost):
        ReplicationSink(j, ["a:1", "b:2"], quorum=3)
    j.close()


def test_quorum_default_is_strict_majority_and_2k_gt_n_enforced(tmp_path):
    """Quorum intersection needs 2K > N. The old (N+1)//2 default gave
    K=1 for N=2 — two DISJOINT single-replica 'quorums', so a promoted
    standby's epoch frame could commit via one replica while the
    deposed primary kept ACKing via the other (split brain). Default is
    now a strict majority, and an explicit non-intersecting K is
    rejected at construction."""
    pdir = str(tmp_path / "p")
    os.makedirs(pdir)
    j = JobJournal(pdir)
    for n, want in ((1, 1), (2, 2), (3, 2), (4, 3), (5, 3)):
        sink = ReplicationSink(j, [f"r{i}:1" for i in range(n)])
        assert sink.quorum == want == n // 2 + 1
        assert 2 * sink.quorum > n
        sink.close()
    for n, k in ((2, 1), (4, 2), (5, 2)):
        with pytest.raises(ReplicaQuorumLost, match="intersection"):
            ReplicationSink(j, [f"r{i}:1" for i in range(n)], quorum=k)
    j.close()


def test_standby_min_reachable_defaults_to_majority(tmp_path):
    sb = Standby("nope.sock", ["a:1", "b:2"], str(tmp_path / "s"))
    assert sb.min_reachable == 2  # N=2: a 1-replica minority view
    sb3 = Standby("nope.sock", ["a:1", "b:2", "c:3"], str(tmp_path / "s3"))
    assert sb3.min_reachable == 2


# ---- fencing / promotion -------------------------------------------------


def test_standby_promotion_fences_old_primary(tmp_path):
    j, a_sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    for i in range(5):
        j.append({"t": "accept", "job_id": f"j{i}", "spec": {}})
    assert a_sink.epoch == 1

    # standby B: adopt the newest-reign replica chain, open epoch 2
    b_dir = str(tmp_path / "standby")
    report = pull_chain(targets, b_dir)
    assert report["reachable"] == 2
    b_j = JobJournal(b_dir, compactor=serve_compactor)
    b_sink = ReplicationSink(b_j, targets, node="B")
    b_j.sink = b_sink
    assert b_sink.begin_epoch() == 2
    assert b_sink.quorum_ok()

    # the deposed primary's next write meets the fence: no ack, flagged
    j.append({"t": "note", "msg": "doomed write from the old reign"})
    assert a_sink.fenced
    assert not a_sink.quorum_ok()
    with pytest.raises(PrimaryFenced) as ei:
        a_sink.check_admission()
    assert ei.value.epoch == 2

    # the doomed tail never reached any replica; B's next append lands
    # on chains that are byte-identical to B's own
    b_j.append({"t": "accept", "job_id": "b1", "spec": {}})
    want = _chain_bytes(b_dir)
    for r in replicas:
        assert _chain_bytes(r.store.dir) == want
        assert "doomed write" not in "".join(
            _chain_bytes(r.store.dir).values()
        )
    a_sink.close()
    j.close()
    b_sink.close()
    b_j.close()


def test_deposed_primary_divergent_tail_discarded_on_rejoin(tmp_path):
    """After a failover, the old primary's un-quorumed tail is exactly
    the history the new primary's resync must discard: re-shipping the
    active segment wholesale overwrites it."""
    j, a_sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    j.append({"t": "accept", "job_id": "j0", "spec": {}})

    b_dir = str(tmp_path / "standby")
    pull_chain(targets, b_dir)
    b_j = JobJournal(b_dir, compactor=serve_compactor)
    b_sink = ReplicationSink(b_j, targets, node="B")
    b_j.sink = b_sink
    b_sink.begin_epoch()

    # replica 0 carries a divergent tail (a frame only the old reign
    # ever shipped it — simulated by a direct store write)
    t = replicas[0].store.tip()
    from primesim_tpu.serve.journal import _frame

    replicas[0].store.apply_append(
        t["seq"], t["crc"], _frame({"t": "note", "msg": "orphan tail"})
    )
    b_j.append({"t": "accept", "job_id": "b1", "spec": {}})
    want = _chain_bytes(b_dir)
    for r in replicas:
        assert _chain_bytes(r.store.dir) == want
    assert "orphan tail" not in "".join(
        _chain_bytes(replicas[0].store.dir).values()
    )
    a_sink.close()
    j.close()
    b_sink.close()
    b_j.close()


def test_standby_requires_reachable_quorum_to_promote(tmp_path):
    j, a_sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    j.append({"t": "accept", "job_id": "j0", "spec": {}})
    for r in replicas:
        r.die()
    time.sleep(0.05)
    sb = Standby("nope.sock", targets, str(tmp_path / "standby"),
                 grace_s=0.0, min_reachable=1)
    with pytest.raises(ReplicaQuorumLost):
        sb.promote_pull()
    a_sink.close()
    j.close()


def test_pull_chain_prefers_newest_epoch_over_longer_stale_tail(tmp_path):
    """Invariant A at promotion time: a deposed primary's un-quorumed
    tail can sit on ONE replica and be LONGER than the new reign's
    quorum-ACKed chain. A later promotion must adopt the chain holding
    the newest epoch frame — never the stale tail, however long."""
    # reign 1 (epoch 1): primary A replicates to r0 only
    r0 = ReplicaServer(str(tmp_path / "r0"), "127.0.0.1:0")
    t0 = r0.start()
    a_dir = str(tmp_path / "a")
    os.makedirs(a_dir)
    a_j = JobJournal(a_dir)
    a_sink = ReplicationSink(a_j, [t0], node="A")
    a_j.sink = a_sink
    a_sink.begin_epoch()
    a_j.append({"t": "accept", "job_id": "j0", "spec": {}})

    # reign 2 (epoch 2): standby B promotes off r0's chain but its own
    # reign replicates to r1 only (the partition's other half) — r1
    # carries epoch 2 and the quorum-ACKed job of the new reign
    r1 = ReplicaServer(str(tmp_path / "r1"), "127.0.0.1:0")
    t1 = r1.start()
    b_dir = str(tmp_path / "b")
    pull_chain([t0], b_dir)
    b_j = JobJournal(b_dir)
    b_sink = ReplicationSink(b_j, [t1], node="B")
    b_j.sink = b_sink
    assert b_sink.begin_epoch() == 2
    b_j.append({"t": "accept", "job_id": "acked-by-reign-2", "spec": {}})
    assert b_sink.quorum_ok()

    # the partitioned A keeps shipping its reign-1 tail to r0 — r0
    # never hears epoch 2, so nothing fences these, and r0's chain
    # grows LONGER than r1's while staying on the deposed epoch
    for i in range(8):
        a_j.append({"t": "accept", "job_id": f"stale{i}", "spec": {}})

    # r0's chain is longer (by records) than r1's — by tip alone the
    # stale chain would win and the quorum-ACKed job would vanish
    assert r0.store.tip()["records"] > r1.store.tip()["records"]
    report = pull_chain([t0, t1], str(tmp_path / "c"))
    assert report["source"] == t1
    adopted = "".join(_chain_bytes(str(tmp_path / "c")).values())
    assert "acked-by-reign-2" in adopted
    a_sink.close()
    a_j.close()
    b_sink.close()
    b_j.close()


def test_compaction_preserves_fencing_epoch(tmp_path):
    """serve_compactor only knows accept/state/drain — but a compaction
    BASE propagates to every replica and becomes the ONLY copy of the
    chain, so compact() itself must re-emit the newest epoch frame. A
    replica restarted over a compacted chain must still recover the
    fence (epochs never regress to 0)."""
    from primesim_tpu.serve.journal import fold_records
    from primesim_tpu.serve.replicate import max_epoch

    j, sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    for i in range(6):
        j.append(_accept_rec(i))
        j.append({"t": "state", "job_id": f"j{i}", "state": "DONE"})
    assert sink.epoch == 1
    j.compact()
    records, _ = j.replay()
    assert max_epoch(records) == 1  # survived the primary's own BASE
    # the fold is untouched by the preserved frame
    jobs, _clean = fold_records(records)
    assert len(jobs) == 6

    # a replica reborn over the compacted chain recovers the fence from
    # disk: the deposed reign (epoch 0 < 1) stays fenced after restart
    reborn = ReplicaServer(replicas[0].store.dir, "127.0.0.1:0")
    assert reborn.epoch == 1
    assert reborn.handle({"verb": "repl.hello", "epoch": 0})["fenced"]
    sink.close()
    j.close()


def test_diverged_rolled_prefix_forces_full_resync(tmp_path):
    """Seq ranges alone cannot prove a follower's chain is a prefix:
    a deposed primary whose un-quorumed tail crossed a roll boundary
    leaves rolled segments at the SAME seqs with different bytes. The
    tip-CRC check must catch this and fall back to reset + full resync
    — otherwise the follower counts toward quorum while its rolled
    prefix silently diverges (breaking fsck --compare invariant C)."""
    # the deposed reign's chain: same segment layout, different history
    stale_dir = str(tmp_path / "stale")
    os.makedirs(stale_dir)
    stale = JobJournal(stale_dir, segment_records=3)
    for i in range(7):  # crosses two roll boundaries
        stale.append({"t": "accept", "job_id": f"stale{i}", "spec": {}})
    stale.close()
    # the follower inherited that chain verbatim (it was the deposed
    # primary's only reachable replica)
    r_dir = str(tmp_path / "replica")
    shutil.copytree(stale_dir, r_dir)
    rep = ReplicaServer(r_dir, "127.0.0.1:0")
    target = rep.start()

    # the new reign's chain, built BEFORE the link comes up so its
    # first sync sees the same seq range the follower reports: same
    # segment layout (same record cadence), entirely different bytes
    pdir = str(tmp_path / "primary")
    os.makedirs(pdir)
    j = JobJournal(pdir, segment_records=3)
    j.append({"t": "epoch", "epoch": 2, "node": "B"})
    for i in range(6):
        j.append({"t": "accept", "job_id": f"new{i}", "spec": {}})
    sink = ReplicationSink(j, [target], node="B")
    j.sink = sink
    sink.epoch = 2
    # the range check alone would pass (follower tip seq sits inside
    # our chain); only the tip-CRC check notices the divergence
    sink.heartbeat()
    assert sink.quorum_ok()
    want = _chain_bytes(pdir)
    got = _chain_bytes(rep.store.dir)
    assert got == want  # EVERY segment, rolled prefix included
    assert "stale" not in "".join(got.values())
    assert run_compare(pdir, r_dir).clean
    sink.close()
    j.close()


# ---- fsck --compare ------------------------------------------------------


def test_fsck_compare_prefix_is_clean(tmp_path):
    j, sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    for i in range(6):
        j.append({"t": "accept", "job_id": f"j{i}", "spec": {}})
    replicas[0].die()
    time.sleep(0.05)
    j.append({"t": "accept", "job_id": "late", "spec": {}})
    # replica 0 is one durable frame behind: a clean prefix, not corrupt
    res = run_compare(pdir, replicas[0].store.dir)
    assert res.clean
    assert res.checked["frames_compared"] > 0
    sink.close()
    j.close()


def test_fsck_compare_divergence_is_corrupt(tmp_path):
    j, sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    for i in range(3):
        j.append({"t": "accept", "job_id": f"j{i}", "spec": {}})
    sink.close()
    j.close()
    bad = str(tmp_path / "bad")
    shutil.copytree(pdir, bad)
    from primesim_tpu.serve.journal import _frame, _scan_lines, _unframe

    p = os.path.join(bad, "journal.jsonl")
    lines = _scan_lines(p)
    rec = _unframe(lines[-1])
    rec["job_id"] = "evil"
    lines[-1] = _frame(rec)  # validly framed, different history
    with open(p, "w") as f:
        f.write("\n".join(lines) + "\n")
    res = run_compare(pdir, bad)
    assert not res.clean
    assert any("diverges" in f.detail for f in res.corrupt)


def test_fsck_compare_cli_exit_codes(tmp_path, capsys):
    from primesim_tpu.cli import main

    j, sink, replicas, targets, pdir = _replicated_journal(tmp_path)
    j.append({"t": "accept", "job_id": "j0", "spec": {}})
    sink.close()
    j.close()
    rc = main(["fsck", "--compare", pdir, replicas[0].store.dir])
    assert rc == 0
    bad = str(tmp_path / "bad")
    os.makedirs(bad)
    bj = JobJournal(bad)
    bj.append({"t": "accept", "job_id": "other-history", "spec": {}})
    bj.close()
    rc = main(["fsck", "--compare", pdir, bad, "--format", "json"])
    assert rc == 2
    captured = capsys.readouterr()
    err = json.loads(captured.err.splitlines()[-1])
    assert err["error"]["type"] == "FsckCorrupt"


# ---- client failover -----------------------------------------------------


def test_client_rotates_to_live_failover_target(tmp_path):
    """A comma-separated target list rides out a dead first entry: the
    connect-phase failure rotates the client onto the standby, which
    answers — the submit/watch survive-a-promotion path."""
    import threading

    from primesim_tpu.serve.client import ServeClient
    from primesim_tpu.serve.server import PrimeServer

    srv = PrimeServer(small_test_config(4), state_dir=str(tmp_path / "s"),
                      buckets=((2, 1),), chunk_steps=16)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    deadline = time.time() + 60
    while not os.path.exists(srv.socket_path):
        assert time.time() < deadline, "server socket never appeared"
        time.sleep(0.01)
    dead = str(tmp_path / "dead.sock")
    cli = ServeClient(f"{dead},{srv.socket_path}", timeout_s=30.0,
                      max_reconnects=2)
    assert cli.targets == [dead, srv.socket_path]
    health = cli.health()
    assert health["ok"]
    assert cli.target == srv.socket_path  # rotated off the dead entry
    assert cli.reconnects >= 1
    cli.drain()


# ---- subprocess acceptance: lose the primary's DISK ----------------------


def _cfg():
    return small_test_config(4)


def _solo_result(cfg, synth_spec, chunk_steps=16):
    from primesim_tpu.serve.scheduler import parse_synth_spec
    from primesim_tpu.sim.engine import Engine

    eng = Engine(cfg, parse_synth_spec(synth_spec, cfg.n_cores, True),
                 chunk_steps=chunk_steps)
    eng.run()
    return (
        [int(c) for c in eng.cycles],
        {k: [int(x) for x in v] for k, v in eng.counters.items()},
    )


def _spawn(tmp_path, argv, ready_prefix):
    """Run a primetpu CLI subcommand; scrape its stderr readiness line
    and return (proc, line)."""
    code = ("import sys; from primesim_tpu.cli import main; "
            "sys.exit(main(%r))" % (argv,))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    deadline = time.time() + 240
    line = ""
    while True:
        if proc.poll() is not None:
            raise AssertionError(
                f"process died before readiness: "
                + proc.stderr.read().decode()[-2000:]
            )
        line = proc.stderr.readline().decode()
        if ready_prefix in line:
            return proc, line.strip()
        assert time.time() < deadline, f"no {ready_prefix!r} line"


def _scrape_target(line):
    # "...: listening on HOST:PORT (..." -> HOST:PORT
    return line.split("listening on ", 1)[1].split(" ", 1)[0].rstrip("(")


@pytest.mark.slow
def test_subprocess_primary_disk_loss_failover_bit_exact(tmp_path):
    """The acceptance story: kill -9 the primary AND DELETE its state
    dir mid-flight. The standby promotes off the replicas, every ACKed
    job reaches DONE bit-exact with solo runs, and `fsck --compare`
    holds the new primary's chain to frame-for-frame agreement with
    each replica."""
    from primesim_tpu.cli import main as cli_main
    from primesim_tpu.serve.client import ServeClient

    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        f.write(_cfg().to_json())
    r_dirs = [str(tmp_path / f"replica{i}") for i in range(2)]
    procs = []
    try:
        r_targets = []
        for d in r_dirs:
            p, line = _spawn(
                tmp_path, ["replica", "--dir", d, "--tcp", "127.0.0.1:0"],
                "replica: listening on",
            )
            procs.append(p)
            r_targets.append(_scrape_target(line))
        replicas_arg = ",".join(r_targets)

        a_dir = str(tmp_path / "primary-a")
        pa, line = _spawn(
            tmp_path,
            ["serve", cfg_path, "--state-dir", a_dir,
             "--tcp", "127.0.0.1:0", "--buckets", "2x1,1x4",
             "--chunk-steps", "16", "--replicas", replicas_arg],
            "serve: listening on",
        )
        procs.append(pa)
        assert "replicated x2" in line
        a_target = _scrape_target(line)

        b_dir = str(tmp_path / "standby-b")
        pb, _ = _spawn(
            tmp_path,
            ["serve", cfg_path, "--state-dir", b_dir,
             "--tcp", "127.0.0.1:0", "--buckets", "2x1,1x4",
             "--chunk-steps", "16", "--replicas", replicas_arg,
             "--standby-of", a_target, "--takeover-grace", "1.0",
             "--idle-exit", "3.0"],
            "serve: standby of",
        )
        procs.append(pb)

        specs = [SMALL_SYNTH.format(31), SMALL_SYNTH.format(32),
                 "fft_like:n_phases=3,points_per_core=32,ins_per_mem=4,"
                 "seed=33"]
        cli = ServeClient(a_target, timeout_s=60.0)
        ids = [cli.submit(synth=s, client="c")["job_id"] for s in specs]

        # kill -9 AND lose the disk: nothing of A survives
        pa.send_signal(signal.SIGKILL)
        pa.wait(timeout=60)
        shutil.rmtree(a_dir)

        # the standby notices, promotes, prints its readiness line
        deadline = time.time() + 240
        b_target = None
        while b_target is None:
            assert time.time() < deadline, "standby never promoted"
            line = pb.stderr.readline().decode()
            if "serve: listening on" in line:
                assert "replicated x2" in line
                b_target = _scrape_target(line)

        cli2 = ServeClient(b_target, timeout_s=60.0)
        results = {i: cli2.wait(i, timeout_s=240.0) for i in ids}
        pb.communicate(timeout=240)
        assert pb.returncode == 0
    finally:
        for p in procs:
            p.kill()

    for spec, i in zip(specs, ids):
        assert results[i]["state"] == "DONE", (i, results[i])
        cyc, ctr = _solo_result(_cfg(), spec)
        assert results[i]["result"]["core_cycles"] == cyc
        assert results[i]["result"]["counters"] == ctr
    for d in r_dirs:
        assert cli_main(["fsck", "--compare", b_dir, d]) == 0
