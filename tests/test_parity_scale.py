"""Engine-vs-golden parity at 64 and 256 cores (SURVEY.md §4a).

The flagship configs run NW > 1 sharer words (NW = ceil(cores/32)); these
tests pin the multi-word paths the small-parity suite never touches: the
`vsh` word-select in the L1 probes, the `unpack_bits` reshape to [C, C],
the masked `join_word` scatter, and back-invalidation over sharers above
bit 31. `readers_writer` populates every sharer word (verified: word 7 at
256 cores); `false_sharing` then invalidates across them.
"""

import numpy as np
import pytest

from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
from primesim_tpu.trace import synth

from test_parity import assert_parity


def scale_machine(n_cores: int, mesh_x: int, mesh_y: int, **kw) -> MachineConfig:
    d = dict(
        n_cores=n_cores,
        n_banks=min(n_cores, 64),
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=16384, ways=4, line=64, latency=10),
        noc=NocConfig(mesh_x=mesh_x, mesh_y=mesh_y, link_lat=1, router_lat=1),
        dram_lat=100,
        quantum=500,
    )
    d.update(kw)
    return MachineConfig(**d)


@pytest.mark.parametrize(
    "gen",
    [
        lambda n: synth.readers_writer(n, n_rounds=2, block_lines=4, seed=31),
        lambda n: synth.false_sharing(n, n_mem_ops=24, n_hot_lines=4, seed=32),
    ],
    ids=["readers_writer", "false_sharing"],
)
def test_parity_64core_two_sharer_words(gen):
    cfg = scale_machine(64, 8, 8)
    assert_parity(cfg, gen(64), chunk_steps=64)


def test_parity_64core_sync():
    # locks + barriers with cores above bit 31 in the sync tables
    cfg = scale_machine(64, 8, 8)
    assert_parity(
        cfg, synth.barrier_phases(64, n_phases=2, work_per_phase=6, seed=33),
        chunk_steps=64,
    )
    assert_parity(
        cfg, synth.lock_contention(64, n_critical=4, n_locks=4, seed=34),
        chunk_steps=64,
    )


def test_parity_256core_eight_sharer_words():
    # all 8 sharer words populated (readers_writer: every core shares the
    # producer's block); back-invalidation + upgrade invalidations sweep
    # the full word range
    cfg = scale_machine(256, 16, 16)
    tr = synth.readers_writer(256, n_rounds=2, block_lines=4, seed=35)
    g_sharer_words = MachineConfig.n_sharer_words.fget(cfg)
    assert g_sharer_words == 8
    assert_parity(cfg, tr, chunk_steps=80)


@pytest.mark.slow
def test_parity_256core_false_sharing_local_runs():
    cfg = scale_machine(256, 16, 16, local_run_len=4)
    assert_parity(
        cfg,
        synth.false_sharing(256, n_mem_ops=16, n_hot_lines=2, seed=36),
        chunk_steps=80,
    )
