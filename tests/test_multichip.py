"""Multi-chip sharding parity: the sharded engine must be bit-exact.

Runs the vectorized engine over the 8-virtual-device CPU mesh (conftest
forces ``xla_force_host_platform_device_count=8``) with cores/banks sharded
over the tile axis, and asserts cycle counts and every stat counter match
the single-device run and the golden scalar model. This is the
single-host stand-in for PriME's multi-node MPI runs (SURVEY.md §4d).
"""

import jax
import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.parallel.sharding import AXIS, tile_mesh
from primesim_tpu.sim.engine import Engine
from primesim_tpu.trace import synth


def _run_all(cfg, trace, mesh):
    g = GoldenSim(cfg, trace)
    g.run()
    e1 = Engine(cfg, trace, chunk_steps=64)
    e1.run()
    e8 = Engine(cfg, trace, chunk_steps=64, mesh=mesh)
    e8.run()
    return g, e1, e8


def test_eight_device_mesh_exists():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize(
    "gen",
    [
        lambda n: synth.uniform_random(n, n_mem_ops=80, seed=7),
        lambda n: synth.false_sharing(n, n_mem_ops=40, seed=3),
        lambda n: synth.fft_like(n, seed=5),
    ],
)
def test_sharded_parity(gen):
    cfg = small_test_config(n_cores=16, n_banks=8)
    trace = gen(16)
    mesh = tile_mesh(8)
    g, e1, e8 = _run_all(cfg, trace, mesh)
    np.testing.assert_array_equal(e8.cycles, g.cycles)
    np.testing.assert_array_equal(e8.cycles, e1.cycles)
    c_g, c_1, c_8 = g.counters, e1.counters, e8.counters
    for k in c_g:
        np.testing.assert_array_equal(c_8[k], c_g[k], err_msg=k)
        np.testing.assert_array_equal(c_8[k], c_1[k], err_msg=k)


def test_state_is_actually_sharded():
    cfg = small_test_config(n_cores=16, n_banks=8)
    trace = synth.stream(16)
    mesh = tile_mesh(8)
    e = Engine(cfg, trace, mesh=mesh)
    shardings = {
        "cycles": e.state.cycles.sharding,
        "dirm": e.state.dirm.sharding,
        "events": e.events.sharding,
    }
    for name, s in shardings.items():
        spec = s.spec
        assert spec and spec[0] == AXIS, (name, spec)
    # and it still runs to completion sharded
    e.run()
    g = GoldenSim(cfg, trace)
    g.run()
    np.testing.assert_array_equal(e.cycles, g.cycles)


def test_global_tile_mesh_single_process():
    # parallel.distributed: in a single-process job the global mesh equals
    # the local-device mesh and the engine runs bit-exact on it (multi-host
    # behavior is XLA's SPMD contract over the same code path)
    from primesim_tpu.parallel.distributed import (
        global_tile_mesh,
        process_info,
    )

    info = process_info()
    assert info["process_count"] == 1 and info["global_devices"] == 8
    mesh = global_tile_mesh()
    cfg = small_test_config(8, n_banks=8)
    tr = synth.readers_writer(8, n_rounds=2, seed=92)
    e = Engine(cfg, tr, chunk_steps=16, mesh=mesh)
    e.run()
    g = GoldenSim(cfg, tr)
    g.run()
    np.testing.assert_array_equal(e.cycles, g.cycles)


def test_sharded_parity_256core():
    # VERDICT r4 #7: multi-chip correctness beyond toy shapes — 256 cores
    # / 256 banks sharded over all 8 devices, bit-exact vs the golden
    # scalar model (and transitively vs the unsharded engine, proven by
    # the other parity suites on the same generators)
    from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig

    cfg = MachineConfig(
        n_cores=256, n_banks=256,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=12),
        noc=NocConfig(mesh_x=16, mesh_y=16),
        quantum=600,
    )
    tr = synth.readers_writer(256, n_rounds=2, block_lines=4, seed=93)
    e = Engine(cfg, tr, chunk_steps=64, mesh=tile_mesh(8))
    e.run()
    g = GoldenSim(cfg, tr)
    g.run()
    np.testing.assert_array_equal(e.cycles, g.cycles)
    ec = e.counters
    for k, v in g.counters.items():
        np.testing.assert_array_equal(ec[k], v, err_msg=k)


def test_sharded_step_never_allgathers_directory():
    # the round-2 regression's failure mode: a layout/sharding slip that
    # makes XLA materialize the FULL sharers/llc_meta array on every
    # device each step. Compile the sharded chunk and assert no
    # all-gather/all-reduce touches a directory-shaped operand.
    import re

    from primesim_tpu.config.machine import CacheConfig, MachineConfig, NocConfig
    from primesim_tpu.parallel.sharding import shard_events, shard_state
    from primesim_tpu.sim.engine import run_chunk
    from primesim_tpu.sim.state import init_state

    cfg = MachineConfig(
        n_cores=256, n_banks=256,
        l1=CacheConfig(size=1024, ways=2, line=64, latency=2),
        llc=CacheConfig(size=4096, ways=4, line=64, latency=12),
        noc=NocConfig(mesh_x=16, mesh_y=16),
        quantum=600,
    )
    tr = synth.false_sharing(256, n_mem_ops=8, seed=94)
    mesh = tile_mesh(8)
    import jax.numpy as jnp

    events = shard_events(mesh, jnp.asarray(tr.line_events(cfg.line_bits)))
    st = shard_state(mesh, init_state(cfg))
    txt = run_chunk.lower(cfg, 4, events, st, has_sync=False).compile().as_text()
    B_S2 = cfg.n_banks * cfg.llc.sets  # full (unsharded) leading dim
    bad = [
        l
        for l in txt.splitlines()
        if re.search(r"all-gather|all-reduce", l) and f"[{B_S2}," in l
    ]
    assert not bad, "directory arrays all-gathered:\n" + "\n".join(bad[:5])
