"""Memory-controller queueing (cfg.dram_queue — SURVEY.md §2 #7's
"queueing model per controller", VERDICT r4 #10): hand-computed golden
charges, cross-step controller-clock carry, and golden-vs-engine
bit-exact parity."""

import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import EV_LD, from_event_lists

from test_parity import assert_parity


def qcfg(n=4, **kw):
    kw.setdefault("n_banks", 4)
    kw.setdefault("quantum", 400)
    return small_test_config(n, dram_queue=True, **kw)


def test_same_bank_misses_queue():
    # lines 0 and 4 both miss at bank 0 in the same step (different
    # sets, so both win arbitration). Core 1's access ranks second and
    # waits for core 0's controller occupancy.
    tr = from_event_lists([[(EV_LD, 4, 0)], [(EV_LD, 4, 4 * 64)], [], []])
    g = GoldenSim(qcfg(), tr)
    g.run()
    g0 = GoldenSim(small_test_config(4, n_banks=4, quantum=400), tr)
    g0.run()
    assert g.counters["dram_queue_cycles"].sum() > 0
    assert g.cycles.max() > g0.cycles.max()
    # exactly one of the two waited
    waits = g.counters["dram_queue_cycles"]
    assert (waits > 0).sum() == 1


def test_different_banks_no_queue():
    tr = from_event_lists([[(EV_LD, 4, 0)], [(EV_LD, 4, 64)], [], []])
    g = GoldenSim(qcfg(), tr)
    g.run()
    assert g.counters["dram_queue_cycles"].sum() == 0


def test_controller_clock_carries_across_steps():
    # core 0 streams misses to bank 0 on consecutive steps; a trailing
    # same-bank miss from core 1 queues behind the CARRIED clock even
    # though it is the only access of its step
    tr = from_event_lists(
        [
            [(EV_LD, 4, 0), (EV_LD, 4, 4 * 64), (EV_LD, 4, 8 * 64)],
            [(EV_LD, 400, 12 * 64)],  # arrives later (long pre batch)
            [],
            [],
        ]
    )
    g = GoldenSim(qcfg(dram_service=150), tr)
    g.run()
    assert g.counters["dram_queue_cycles"][1] > 0


@pytest.mark.parametrize(
    "gen", ["false_sharing", "uniform_random", "barrier_phases"]
)
@pytest.mark.slow
def test_parity_dram_queue(gen):
    cfg = qcfg(8, n_banks=4)
    tr = {
        "false_sharing": lambda: synth.false_sharing(8, n_mem_ops=40, seed=21),
        "uniform_random": lambda: synth.uniform_random(8, n_mem_ops=50, seed=22),
        "barrier_phases": lambda: synth.barrier_phases(8, n_phases=2, seed=23),
    }[gen]()
    assert_parity(cfg, tr, chunk_steps=50)


@pytest.mark.slow
def test_parity_dram_queue_with_router_and_runs():
    # all the timing models stacked: hop-by-hop router + controller
    # queue + local runs + O3 — still bit-exact
    from primesim_tpu.config.machine import CoreConfig, NocConfig
    from primesim_tpu.trace.format import fold_ins

    cfg = small_test_config(
        8, n_banks=8, quantum=500, local_run_len=4, dram_queue=True,
        dram_service=40,
        core=CoreConfig(o3_overlap_256=64),
        noc=NocConfig(mesh_x=4, mesh_y=2, contention=True,
                      contention_model="router"),
    )
    tr = fold_ins(synth.fft_like(8, n_phases=2, points_per_core=12, seed=24))
    assert_parity(cfg, tr, chunk_steps=16)
