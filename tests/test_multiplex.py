"""Multiprogrammed workloads (SURVEY.md §2 parallelism table / PriME's
multiple-Pin-processes mode): several programs' traces multiplexed into
one machine's core axis, sharing the LLC/NoC/DRAM but with disjoint
address spaces and sync objects."""

import numpy as np
import pytest

from primesim_tpu.config.machine import small_test_config
from primesim_tpu.golden.sim import GoldenSim
from primesim_tpu.trace import synth
from primesim_tpu.trace.format import (
    EV_BARRIER,
    EV_LD,
    EV_ST,
    multiplex,
)

from test_parity import assert_parity


def test_address_spaces_disjoint():
    a = synth.false_sharing(4, n_mem_ops=20, seed=1)
    b = synth.false_sharing(4, n_mem_ops=20, seed=1)  # SAME program twice
    m = multiplex([a, b])
    assert m.n_cores == 8
    ty = m.events[:, :, 0]
    mem = (ty == EV_LD) | (ty == EV_ST)
    addrs_a = set(np.unique(m.events[:4, :, 2][mem[:4]]).tolist())
    addrs_b = set(np.unique(m.events[4:, :, 2][mem[4:]]).tolist())
    # identical programs, but NO shared lines after multiplexing
    assert addrs_a and addrs_b and not (addrs_a & addrs_b)


def test_barrier_ids_offset():
    a = synth.barrier_phases(4, n_phases=2, seed=2)
    b = synth.barrier_phases(4, n_phases=2, seed=3)
    m = multiplex([a, b])
    bar = m.events[:, :, 0] == EV_BARRIER
    bids_a = set(np.unique(m.events[:4, :, 2][bar[:4]]).tolist())
    bids_b = set(np.unique(m.events[4:, :, 2][bar[4:]]).tolist())
    assert bids_a and bids_b and not (bids_a & bids_b)


def test_mixed_addressing_rejected():
    a = synth.stream(4, n_mem_ops=10, seed=4)
    la = a.line_events(6)
    from primesim_tpu.trace.format import Trace

    b_line = Trace(la, a.lengths, line_addressed=True, line_bits=6)
    with pytest.raises(ValueError, match="addressing"):
        multiplex([a, b_line])


def test_window_overflow_rejected():
    from primesim_tpu.trace.format import from_event_lists

    big = from_event_lists([[(EV_LD, 4, 2**30)]])
    with pytest.raises(ValueError, match="window"):
        multiplex([big, big], prog_bits=4)


def test_parity_multiprogrammed():
    # two different programs contending for one small shared uncore:
    # golden and engine bit-exact, and each program completes
    cfg = small_test_config(8, n_banks=4, quantum=400)
    m = multiplex(
        [
            synth.false_sharing(4, n_mem_ops=30, seed=5),
            synth.stream(4, n_mem_ops=30, seed=6),
        ]
    )
    assert_parity(cfg, m, chunk_steps=32)


def test_multiprogram_sync_isolation():
    # two barrier programs: each program's barriers release independently
    # (offset ids), so per-core barrier_waits match the solo runs
    cfg8 = small_test_config(8, n_banks=4, quantum=400)
    cfg4 = small_test_config(4, n_banks=4, quantum=400)
    a = synth.barrier_phases(4, n_phases=2, seed=7)
    b = synth.barrier_phases(4, n_phases=3, seed=8)
    m = multiplex([a, b])
    g = GoldenSim(cfg8, m)
    g.run()
    ga = GoldenSim(cfg4, a)
    ga.run()
    gb = GoldenSim(cfg4, b)
    gb.run()
    np.testing.assert_array_equal(
        g.counters["barrier_waits"][:4], ga.counters["barrier_waits"]
    )
    np.testing.assert_array_equal(
        g.counters["barrier_waits"][4:], gb.counters["barrier_waits"]
    )


def test_cli_multiprogrammed_run(tmp_path, capsys):
    import json
    import os

    from primesim_tpu.cli import main

    a = tmp_path / "a.ptpu"
    b = tmp_path / "b.ptpu"
    synth.false_sharing(4, n_mem_ops=20, seed=9).save(str(a))
    synth.stream(4, n_mem_ops=20, seed=10).save(str(b))
    cfg_path = str(tmp_path / "m.json")
    with open(cfg_path, "w") as f:
        f.write(small_test_config(8, n_banks=4).to_json())
    rc = main(
        ["run", cfg_path, "--trace", str(a), "--trace", str(b),
         "--chunk-steps", "16"]
    )
    assert rc == 0
    d = json.loads(capsys.readouterr().out)
    assert d["detail"]["n_cores"] == 8
    assert d["detail"]["instructions"] > 0


def test_multiprogram_lock_isolation():
    # regression (r5 review): the lock-table slot hashes from LOW address
    # bits, so high-bit program tags alone let two programs' identical
    # mutex addresses serialize on one slot. With the low-bit fold, each
    # program's lock behavior matches its solo run exactly.
    cfg8 = small_test_config(8, n_banks=4, quantum=400)
    cfg4 = small_test_config(4, n_banks=4, quantum=400)
    a = synth.lock_contention(4, n_critical=8, seed=7)
    b = synth.lock_contention(4, n_critical=8, seed=8)
    m = multiplex([a, b])
    g = GoldenSim(cfg8, m)
    g.run()
    ga = GoldenSim(cfg4, a)
    ga.run()
    gb = GoldenSim(cfg4, b)
    gb.run()
    np.testing.assert_array_equal(
        g.counters["lock_acquires"][:4], ga.counters["lock_acquires"]
    )
    np.testing.assert_array_equal(
        g.counters["lock_acquires"][4:], gb.counters["lock_acquires"]
    )
    # the direct guarantee: the two programs' mutexes occupy DISJOINT
    # lock-table slots (the engines hash slots from low address bits)
    from primesim_tpu.trace.format import EV_LOCK

    L = cfg8.lock_slots
    lb = cfg8.line_bits
    lk = m.events[:, :, 0] == EV_LOCK
    slots_a = set(
        ((np.unique(m.events[:4, :, 2][lk[:4]]) >> lb) & (L - 1)).tolist()
    )
    slots_b = set(
        ((np.unique(m.events[4:, :, 2][lk[4:]]) >> lb) & (L - 1)).tolist()
    )
    assert slots_a and slots_b and not (slots_a & slots_b)
