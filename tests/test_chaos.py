"""Chaos subsystem (DESIGN.md §20): plan determinism, per-site fault
trials, the shrinker, idempotent submit, torn-frame protocol handling,
and the invariant-checked campaign end to end."""

import io
import json
import os
import time

import pytest

from primesim_tpu.chaos import campaign as C
from primesim_tpu.chaos import plan as P
from primesim_tpu.chaos import sites
from primesim_tpu.config.machine import small_test_config


@pytest.fixture(autouse=True)
def _no_leftover_runtime():
    """Chaos state is process-global; no test may leak an active plan."""
    sites.deactivate()
    yield
    sites.deactivate()


@pytest.fixture(scope="module")
def golden():
    sites.deactivate()
    return C.golden_run()


def _ev(site, occ, action, **args):
    return P.FaultEvent(site=site, occurrence=occ, action=action,
                        args=tuple(sorted(args.items())))


# ---- plans ---------------------------------------------------------------


def test_plan_generation_deterministic():
    a = P.generate(42)
    b = P.generate(42)
    assert a == b
    assert a.events  # at least one event
    assert P.generate(43) != a or P.generate(44) != a


def test_plan_json_round_trip():
    plan = P.generate(7, classes=("durable", "crashpoint", "socket"))
    again = P.FaultPlan.from_json(plan.to_json())
    assert again == plan
    # and through a file (the artifact path)
    d = json.loads(plan.to_json())
    assert d["seed"] == 7
    assert all(ev["site"] in sites.SITES for ev in d["events"])


def test_plan_events_unique_site_occurrence():
    for seed in range(50):
        plan = P.generate(seed, classes=("durable", "crashpoint",
                                         "socket", "clock"))
        keys = [(e.site, e.occurrence) for e in plan.events]
        assert len(keys) == len(set(keys))
        for e in plan.events:
            cls = sites.SITES[e.site]
            assert e.action in P.ACTIONS[cls]


def test_recv_sites_never_draw_send_actions():
    for seed in range(80):
        plan = P.generate(seed, classes=("socket",))
        for e in plan.events:
            if e.site.endswith(".recv"):
                assert e.action in ("disconnect", "delay")


def test_plan_save_load(tmp_path):
    plan = P.generate(3)
    path = str(tmp_path / "plan.json")
    plan.save(path)
    assert P.FaultPlan.load(path) == plan


# ---- runtime semantics ---------------------------------------------------


def test_events_fire_once_and_occurrences_count():
    plan = P.FaultPlan(seed=0, events=(
        _ev("scheduler.pre-dispatch", 2, "kill"),
    ))
    rt = sites.install(plan, mode="raise")
    sites.crashpoint("scheduler.pre-dispatch")  # occurrence 1: no fire
    with pytest.raises(sites.ChaosCrash):
        sites.crashpoint("scheduler.pre-dispatch")  # occurrence 2
    # fired events never re-fire, even at the same count
    sites.crashpoint("scheduler.pre-dispatch")
    sites.crashpoint("scheduler.pre-dispatch")
    assert rt.counts["scheduler.pre-dispatch"] == 4
    assert rt.injected == [{"site": "scheduler.pre-dispatch",
                            "occurrence": 2, "action": "kill"}]


def test_chaoscrash_is_not_swallowed_by_except_exception():
    assert not issubclass(sites.ChaosCrash, Exception)
    plan = P.FaultPlan(seed=0, events=(_ev("worker.pre-ack", 1, "kill"),))
    sites.install(plan, mode="raise")
    with pytest.raises(sites.ChaosCrash):
        try:
            sites.crashpoint("worker.pre-ack")
        except Exception:  # noqa: BLE001 — the boundary under test
            pytest.fail("protocol boundary absorbed an injected crash")


def test_no_plan_hooks_are_inert():
    assert sites.runtime() is None
    sites.crashpoint("worker.pre-ack")
    sites.durable("journal.append", f=None, data=b"x")
    assert sites.clock_skew("coordinator.clock", 5.0) == 5.0
    clock = time.monotonic
    assert sites.wrap_clock("coordinator.clock", clock) is clock


def test_clock_skew_persists_after_event():
    plan = P.FaultPlan(seed=0, events=(
        _ev("coordinator.clock", 2, "skew", offset_s=10.0),
    ))
    sites.install(plan, mode="raise")
    assert sites.clock_skew("coordinator.clock", 100.0) == 100.0
    assert sites.clock_skew("coordinator.clock", 100.0) == 110.0
    assert sites.clock_skew("coordinator.clock", 100.0) == 110.0


# ---- per-site-class trials (in-process serve stack) ----------------------


def test_torn_journal_write_trial(golden):
    plan = P.FaultPlan(seed=1, events=(
        _ev("journal.append", 2, "torn", frac=0.4),
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert res.ok, res.violations
    assert res.restarts == 1
    assert res.injected[0]["site"] == "journal.append"


def test_fsync_failure_trial(golden):
    plan = P.FaultPlan(seed=2, events=(
        _ev("journal.append", 1, "fsync_fail"),
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert res.ok, res.violations
    assert res.restarts == 1


def test_checkpoint_torn_trial(golden):
    plan = P.FaultPlan(seed=3, events=(
        _ev("checkpoint.write", 2, "torn", frac=0.3),
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert res.ok, res.violations


def test_ack_window_crashpoint_trial(golden):
    """Death between the durable accept and the ACK: the client never
    heard yes, the idempotent resubmit must find the journaled job."""
    plan = P.FaultPlan(seed=4, events=(
        _ev("server.post-journal-pre-ack", 1, "kill"),
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert res.ok, res.violations
    assert res.restarts == 1


def test_scheduler_crashpoints_trial(golden):
    plan = P.FaultPlan(seed=5, events=(
        _ev("scheduler.pre-dispatch", 2, "kill"),
        _ev("scheduler.post-checkpoint", 3, "kill"),
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert res.ok, res.violations
    assert res.restarts == 2


def test_socket_disconnect_trial(golden):
    """Lost reply on the wire: the submit's ACK dies with the
    connection; the client's token-carrying retry must not twin the
    job."""
    plan = P.FaultPlan(seed=6, events=(
        _ev("protocol.recv", 1, "disconnect"),
        _ev("protocol.send", 3, "short_send", frac=0.5),
    ))
    res = C.run_socket_trial(plan, golden=golden)
    assert res.ok, res.violations
    assert len(res.injected) == 2


# ---- the worker's legacy crash knob rides the registry -------------------


def test_worker_crash_knob_installs_crashpoint_plan(tmp_path):
    from primesim_tpu.pool.worker import PoolWorker, SimulatedCrash

    PoolWorker(str(tmp_path / "sock"), "wX",
               crash_after_chunks=2, simulate_crash=True)
    rt = sites.runtime()
    assert rt is not None and rt.mode == "raise"
    [ev] = rt.plan.events
    assert (ev.site, ev.occurrence) == ("worker.post-checkpoint", 2)
    sites.crashpoint("worker.post-checkpoint")  # chunk 1: survives
    with pytest.raises(SimulatedCrash):
        sites.crashpoint("worker.post-checkpoint")  # chunk 2: dies


# ---- S3: torn-frame protocol regression ----------------------------------


def test_read_line_rejects_torn_frame():
    from primesim_tpu.serve.protocol import read_line

    with pytest.raises(ValueError, match="torn protocol frame"):
        read_line(io.BytesIO(b'{"verb":"sub'))
    with pytest.raises(ValueError, match="torn protocol frame"):
        # a torn frame that still PARSES as JSON must not slip through
        read_line(io.BytesIO(b'{"ok":true}'))


def test_read_line_full_frame_and_eof():
    from primesim_tpu.serve.protocol import read_line

    assert read_line(io.BytesIO(b'{"ok":true}\n')) == {"ok": True}
    assert read_line(io.BytesIO(b"")) is None


# ---- S2: idempotent client ------------------------------------------------


def test_client_post_send_retry_only_for_idempotent(monkeypatch):
    from primesim_tpu.serve.client import ServeClient

    calls = {"n": 0}

    def flaky(target, req, timeout_s=30.0, connect_timeout_s=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("link died post-send")
        return {"ok": True}

    monkeypatch.setattr("primesim_tpu.serve.client.request", flaky)
    cli = ServeClient("sock", timeout_s=1.0, max_reconnects=2)
    assert cli._call({"verb": "status"}, idempotent=True)["ok"]
    assert calls["n"] == 2 and cli.reconnects == 1

    calls["n"] = 0
    with pytest.raises(ConnectionError):
        cli._call({"verb": "cancel"})  # not idempotent: no retry
    assert calls["n"] == 1


def test_submit_generates_idempotency_token(monkeypatch):
    from primesim_tpu.serve.client import ServeClient

    seen = []

    def capture(target, req, timeout_s=30.0, connect_timeout_s=None):
        seen.append(req)
        return {"ok": True, "job": {"job_id": "j000001"}}

    monkeypatch.setattr("primesim_tpu.serve.client.request", capture)
    cli = ServeClient("sock")
    cli.submit(synth="s")
    cli.submit(synth="s", idem="tok-7")
    assert seen[0]["idem"] and len(seen[0]["idem"]) == 32
    assert seen[1]["idem"] == "tok-7"
    assert seen[0]["idem"] != seen[1]["idem"]


def test_server_dedups_idempotency_token(tmp_path):
    from primesim_tpu.serve.server import PrimeServer

    srv = PrimeServer(
        small_test_config(4), state_dir=str(tmp_path / "srv"),
        buckets=((2, 1),), chunk_steps=16,
    )
    req = {"verb": "submit", "idem": "tok",
           "synth": "fft_like:n_phases=1,points_per_core=8,seed=1"}
    first = srv._handle(dict(req))
    again = srv._handle(dict(req))
    assert first["ok"] and again["ok"]
    assert again["duplicate"] is True
    assert again["job"]["job_id"] == first["job"]["job_id"]
    assert len(srv.sched.jobs) == 1
    # a DIFFERENT token is a different request
    third = srv._handle({**req, "idem": "tok2"})
    assert third["job"]["job_id"] != first["job"]["job_id"]
    srv.journal.close()


def test_idem_token_survives_journal_replay(tmp_path):
    from primesim_tpu.serve import jobs as J
    from primesim_tpu.serve.journal import JobJournal, fold_records

    d = str(tmp_path / "j")
    os.makedirs(d)
    j = JobJournal(d)
    j.accept(J.Job(job_id="j000001", idem="tok-x", synth="s"))
    j.close()
    recs, _ = JobJournal(d).replay()
    jobs, _ = fold_records(recs)
    assert jobs["j000001"].idem == "tok-x"


# ---- shrinker ------------------------------------------------------------


def test_shrinker_finds_minimal_event_set():
    culprit = _ev("journal.append", 3, "torn", frac=0.5)
    plan = P.FaultPlan(seed=9, events=(
        _ev("scheduler.pre-dispatch", 1, "kill"),
        culprit,
        _ev("checkpoint.write", 2, "delay", s=0.001),
    ))
    trials = []

    def still_fails(p):
        trials.append(p)
        return culprit in p.events

    shrunk = P.shrink(plan, still_fails)
    assert shrunk.events == (culprit,)
    assert trials  # the predicate actually drove the search


def test_shrinker_keeps_interacting_pair():
    a = _ev("journal.append", 1, "torn", frac=0.5)
    b = _ev("scheduler.post-checkpoint", 1, "kill")
    plan = P.FaultPlan(seed=10, events=(
        a, b, _ev("checkpoint.write", 4, "delay", s=0.001),
    ))
    shrunk = P.shrink(
        plan, lambda p: a in p.events and b in p.events
    )
    assert set(shrunk.events) == {a, b}


# ---- the campaign catches a real durability bug --------------------------


def test_deliberate_ack_before_fsync_bug_caught(tmp_path, golden,
                                               monkeypatch):
    """Break the ACK invariant on purpose (accept returns without
    journaling) and the ack-window crashpoint must surface it as an
    invariant-A violation with a shrunk, replayable artifact."""
    from primesim_tpu.serve.journal import JobJournal

    monkeypatch.setattr(JobJournal, "accept", lambda self, job: None)
    # the crash must land AFTER the ACKs (submit returned) — dispatch of
    # the first chunk is exactly that window
    plan = P.FaultPlan(seed=77, events=(
        _ev("scheduler.pre-dispatch", 1, "kill"),
        _ev("checkpoint.write", 9, "delay", s=0.001),  # innocent rider
    ))
    res = C.run_serve_trial(plan, golden=golden)
    assert not res.ok
    assert any("invariant A" in v for v in res.violations)

    # shrink against the invariant that actually broke (fsck alone also
    # catches this bug, so a generic not-ok predicate would accept ANY
    # event set, including the empty-ish rider)
    def lost_ack(p):
        r = C.run_serve_trial(p, golden=golden)
        return any("invariant A" in v for v in r.violations)

    shrunk = P.shrink(plan, lost_ack)
    assert len(shrunk.events) == 1
    assert shrunk.events[0].site == "scheduler.pre-dispatch"

    art = str(tmp_path / "repro.json")
    with open(art, "w") as f:
        json.dump({"seed": 77, "plan": shrunk.as_dict(),
                   "violations": res.violations}, f)
    replay = C.replay_artifact(art)
    assert not replay.ok  # bug still in place: artifact reproduces


def test_fixed_bug_makes_artifact_pass(tmp_path, golden):
    """The same artifact goes green once the bug is gone — the repro
    loop's exit condition."""
    art = str(tmp_path / "repro.json")
    plan = P.FaultPlan(seed=77, events=(
        _ev("scheduler.pre-dispatch", 1, "kill"),
    ))
    with open(art, "w") as f:
        json.dump({"seed": 77, "plan": plan.as_dict()}, f)
    assert C.replay_artifact(art).ok


# ---- e2e seeded campaign --------------------------------------------------


@pytest.mark.slow
def test_seeded_campaign_clean(tmp_path):
    report = C.run_campaign(
        n_trials=6, seed0=900, classes=("durable", "crashpoint"),
        artifact_dir=str(tmp_path / "art"),
    )
    assert report["ok"], report["violations"]
    assert report["trials"] == 6
    assert report["fired_events"] > 0  # the plans actually bit


@pytest.mark.slow
def test_campaign_cli_verb(tmp_path):
    from primesim_tpu.cli import main

    rc = main(["chaos", "--trials", "2", "--seed", "321",
               "--classes", "durable"])
    assert rc == 0
