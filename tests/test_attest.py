"""Result integrity (DESIGN.md §24): the per-chunk fingerprint chain,
ACK attestation at the pool coordinator (hedged-twin comparison,
mismatch -> tiebreak -> SUSPECT, toolchain admission), the sampled
re-execution audit, the offline `primetpu audit` replay, fsck's
attestation-record checks, and the silent-corruption chaos trial's
invariant F.

Determinism discipline: chain heads are sha256 over committed host
state, so every cross-engine assertion here is exact string equality —
any flake IS the bug this subsystem exists to catch.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from primesim_tpu.attest import (
    AttestationError,
    FleetAttest,
    SoloAttest,
    toolchain_fingerprint,
)
from primesim_tpu.attest.chain import comparable, heads_equal
from primesim_tpu.config.machine import small_test_config
from primesim_tpu.pool import DONE, PENDING, SUSPECT, PoolCoordinator
from primesim_tpu.pool.units import build_units
from primesim_tpu.serve.scheduler import parse_synth_spec
from primesim_tpu.sim.engine import Engine
from primesim_tpu.sim.fleet import FleetEngine
from primesim_tpu.sim.supervisor import RunSupervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SYNTH = "fft_like:n_phases=1,points_per_core=8,ins_per_mem=4,seed={}"


def _cfg():
    return small_test_config(4)


def _trace(seed=7):
    cfg = _cfg()
    return cfg, parse_synth_spec(SYNTH.format(seed), cfg.n_cores, True)


def _at(head="a" * 64, chunks=3, start=0, chunk_steps=16):
    return {"head": head, "chunks": chunks, "start": start,
            "chunk_steps": chunk_steps}


# ---- the chain itself ----------------------------------------------------


def test_chain_determinism_solo_vs_fleet():
    """The same workload at the same cadence commits the same chain,
    whether it runs on the solo engine or as a fleet element — the
    cross-engine property every downstream comparison stands on."""
    cfg, trace = _trace()
    solo = Engine(cfg, trace, chunk_steps=16)
    solo.attest = SoloAttest(16)
    solo.run_chunked()  # the chunk-committing path is what observes
    fleet = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    fleet.attest = FleetAttest()
    fleet.attest.track(0, 16, start=0)
    RunSupervisor(fleet, handle_signals=False).run(max_steps=100_000)
    sp, fp = solo.attest.payload(), fleet.attest.payload(0)
    assert sp["head"] and sp["chunks"] > 1
    assert sp == fp


@pytest.mark.slow  # slow: 8-device GSPMD compile; integrity-chaos CI job runs it
def test_chain_determinism_sharded():
    """An 8-virtual-device sharded fleet commits the same chain as the
    single-device fleet: digests are taken from gathered host state,
    never from per-shard views."""
    from primesim_tpu.parallel.sharding import tile_mesh

    cfg = small_test_config(n_cores=16, n_banks=8)
    trace = parse_synth_spec(SYNTH.format(5), cfg.n_cores, True)

    def run(mesh):
        fleet = FleetEngine(cfg, [trace], [{}], chunk_steps=16,
                            mesh=mesh)
        fleet.attest = FleetAttest()
        fleet.attest.track(0, 16, start=0)
        RunSupervisor(fleet, handle_signals=False).run(max_steps=100_000)
        return fleet.attest.payload(0)

    assert run(None) == run(tile_mesh(8))


def test_chunk_digest_sees_every_committed_field():
    """One flipped counter — or one flipped state leaf — changes the
    digest, and therefore every chain head after it: the sensitivity
    the whole subsystem stands on."""
    from primesim_tpu.attest.chain import _host_leaves, chunk_digest, link
    from primesim_tpu.stats.counters import COUNTER_NAMES

    cfg, trace = _trace()
    fleet = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    RunSupervisor(fleet, handle_signals=False).run(max_steps=100_000)
    leaves = [leaf[0] for leaf in _host_leaves(fleet.state)]
    counters = {k: fleet.host_counters[k][0] for k in COUNTER_NAMES}
    args = (int(fleet.steps_run[0]), int(fleet.cycle_base[0]))
    base = chunk_digest(*args, counters, leaves)
    assert base == chunk_digest(*args, dict(counters), list(leaves))
    flip = dict(counters,
                instructions=np.asarray(counters["instructions"]) + 1)
    assert chunk_digest(*args, flip, leaves) != base
    bent = [np.asarray(l).copy() for l in leaves]
    bent[0] = np.where(np.ones_like(bent[0], dtype=bool),
                       np.invert(bent[0]) if bent[0].dtype == bool
                       else bent[0] + 1, bent[0])
    assert chunk_digest(*args, counters, bent) != base
    # divergence propagates through the chain link
    assert link("", base) != link("", chunk_digest(*args, flip, leaves))


def test_chain_incomparable_after_cadence_change():
    """note_cadence (the OOM-halving hook) marks the chain so it can
    never be false-positive-compared against a full-cadence chain."""
    fa = FleetAttest()
    fa.track(0, 16, start=0)
    fa.note_cadence(8)
    halved = fa.payload(0)
    assert not comparable(halved, _at(chunk_steps=16))
    assert comparable(_at(), _at(head="b" * 64))
    assert not heads_equal(_at(), _at(head="b" * 64))


def test_checkpoint_restore_resumes_identical_chain(tmp_path):
    """Crash-resume must re-join the chain exactly: a run checkpointed
    at chunk k and resumed elsewhere commits the same head as the
    uninterrupted run (what makes offline replay comparable at all)."""
    from primesim_tpu.sim.checkpoint import (
        load_element_checkpoint,
        save_element_checkpoint,
    )

    cfg, trace = _trace()
    straight = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    straight.attest = FleetAttest()
    straight.attest.track(0, 16, start=0)
    RunSupervisor(straight, handle_signals=False).run(max_steps=100_000)

    first = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    first.attest = FleetAttest()
    first.attest.track(0, 16, start=0)
    first.step_chunk()
    first.step_chunk()
    path = str(tmp_path / "elem.npz")
    save_element_checkpoint(path, first, 0)

    snap = load_element_checkpoint(path, cfg, trace)
    at = snap.get("attest")
    assert at and at["chunks"] == 2 and at["start"] == 0
    second = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    second.restore_element(0, snap)
    second.attest = FleetAttest()
    second.attest.track(0, 16, start=at["start"], head=at["head"],
                        chunks=at["chunks"])
    RunSupervisor(second, handle_signals=False).run(max_steps=100_000)
    assert second.attest.payload(0) == straight.attest.payload(0)


def test_attest_off_is_bit_exact_and_emits_nothing():
    """--attest off is the dead branch: `attest` stays None, nothing
    observes the engines, and the committed outputs are identical to an
    attested run's (the chain only READS host state)."""
    cfg, trace = _trace()

    def run(on):
        fleet = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
        assert fleet.attest is None  # the default, never flipped by sim
        if on:
            fleet.attest = FleetAttest()
            fleet.attest.track(0, 16, start=0)
        RunSupervisor(fleet, handle_signals=False).run(max_steps=100_000)
        ec = fleet.element_counters(0)
        return {k: int(v.sum()) for k, v in ec.items()} | {
            "cycles": int(fleet.cycles[0].max()),
            "steps": int(fleet.steps_run[0]),
        }

    assert run(False) == run(True)


# ---- coordinator: ack attestation, tiebreak, SUSPECT, toolchain ----------


def _units(n=2, chunk_steps=16):
    cfg = _cfg()
    return cfg, build_units(
        cfg, [], [SYNTH.format(i) for i in range(n)],
        [{} for _ in range(n)], fold=True, chunk_steps=chunk_steps,
        max_steps=100_000,
    )


def _coord(tmp_path, units, **kw):
    kw.setdefault("lease_ttl_s", 5.0)
    kw.setdefault("attest", "chain")
    return PoolCoordinator(units, str(tmp_path / "pool"), **kw)


def _lease(coord, worker, toolchain=None):
    req = {"verb": "lease", "worker": worker}
    if toolchain is not None:
        req["toolchain"] = toolchain
    return coord.handle(req)


def _ack(coord, worker, grant, attest=None, audit=False, value=1):
    u = grant["unit"]
    req = {
        "verb": "ack", "worker": worker, "unit_id": u["unit_id"],
        "epoch": grant["epoch"], "key": u["key"],
        "result": {"metric": "x", "value": value}, "resumed_steps": 0,
    }
    if attest is not None:
        req["attest"] = attest
    if audit:
        req["audit"] = True
    return coord.handle(req)


def test_hedged_twin_mismatch_tiebreak_resolves_and_quarantines(tmp_path):
    """Two comparable chains disagree -> both held, unit voided back to
    PENDING barred to both claimants; the third worker's fresh run
    matches one chain -> DONE with that result, the refuted worker is
    quarantined as SUSPECT and refused at its next lease."""
    cfg, units = _units(1)
    coord = _coord(tmp_path, units)
    good, bad = _at(), _at(head="b" * 64)

    g1 = _lease(coord, "w1")
    assert g1.get("attest") == "chain"
    assert _ack(coord, "w1", g1, attest=good)["accepted"]
    # hedged twin (or re-dispatched loser) acks with a diverging chain
    r = coord.handle({
        "verb": "ack", "worker": "w2",
        "unit_id": g1["unit"]["unit_id"], "epoch": g1["epoch"],
        "key": g1["unit"]["key"],
        "result": {"metric": "x", "value": 2}, "resumed_steps": 0,
        "attest": bad,
    })
    assert r["mismatch"] and coord.counters["attest_mismatches"] == 1
    uid = g1["unit"]["unit_id"]
    assert coord.units[uid]["state"] == PENDING
    assert coord.units[uid]["suspects"] == {"w1", "w2"}
    # neither claimant may take the tiebreak
    assert _lease(coord, "w1").get("idle")
    g3 = _lease(coord, "w3")
    assert g3["fresh"] and g3["checkpoint"] is None
    assert _ack(coord, "w3", g3, attest=good)["accepted"]
    assert coord.units[uid]["state"] == DONE
    assert coord.suspect_workers == {"w2"}
    refused = _lease(coord, "w2")
    assert refused["refused"] == "suspect"
    assert refused["error"]["type"] == "AttestationError"
    coord.close(drained=False)


def test_three_way_divergence_is_terminal_suspect(tmp_path):
    """Tiebreak matches neither held chain: the unit itself parks as
    SUSPECT (terminal, all three payloads preserved in the ledger)."""
    cfg, units = _units(1)
    coord = _coord(tmp_path, units)
    g1 = _lease(coord, "w1")
    uid = g1["unit"]["unit_id"]
    _ack(coord, "w1", g1, attest=_at("a" * 64))
    coord.handle({
        "verb": "ack", "worker": "w2", "unit_id": uid,
        "epoch": g1["epoch"], "key": g1["unit"]["key"],
        "result": {"metric": "x", "value": 2}, "resumed_steps": 0,
        "attest": _at("b" * 64),
    })
    g3 = _lease(coord, "w3")
    r = _ack(coord, "w3", g3, attest=_at("c" * 64))
    assert r["suspect"]
    # terminal SUSPECT is its own state, distinct from POISON
    assert coord.units[uid]["state"] == SUSPECT
    assert len(coord.units[uid]["held"]) == 3
    assert coord.done
    res = {x["unit_id"]: x for x in coord.results()}
    assert res[uid]["state"] == "SUSPECT"
    coord.close(drained=False)
    # the ledger retains every chain for the offline adjudicator
    from primesim_tpu.analysis.fsck import _check_journal_dir

    root = str(tmp_path / "pool")
    records, _ = _check_journal_dir(root, root)
    verdicts = [x for x in records if x.get("t") == "verdict"]
    assert verdicts and verdicts[-1]["outcome"] == "unresolved"
    assert len(verdicts[-1]["held"]) == 3


def test_hedged_twin_agreement_confirms(tmp_path):
    cfg, units = _units(1)
    coord = _coord(tmp_path, units)
    g1 = _lease(coord, "w1")
    _ack(coord, "w1", g1, attest=_at())
    r = coord.handle({
        "verb": "ack", "worker": "w2",
        "unit_id": g1["unit"]["unit_id"], "epoch": g1["epoch"],
        "key": g1["unit"]["key"], "result": {"metric": "x", "value": 1},
        "resumed_steps": 0, "attest": _at(),
    })
    assert r["duplicate"] and not r.get("mismatch")
    assert coord.counters["attest_confirms"] == 1
    assert not coord.suspect_workers
    coord.close(drained=False)


def test_toolchain_mismatch_refused_at_lease(tmp_path):
    cfg, units = _units(1)
    coord = _coord(tmp_path, units)
    ours = toolchain_fingerprint()
    assert set(ours) == {"jax", "jaxlib", "backend"}
    ok = _lease(coord, "w1", toolchain=dict(ours))
    assert ok.get("unit")
    stale = dict(ours, jaxlib="0.0.0-elsewhere")
    r = _lease(coord, "w2", toolchain=stale)
    assert r["refused"] == "toolchain"
    assert r["error"]["type"] == "AttestationError"
    assert "jaxlib" in r["error"]["detail"]
    assert coord.counters["toolchain_refused"] == 1
    coord.close(drained=False)


def test_audit_rate_redispatches_to_other_worker(tmp_path):
    """--audit-rate 1.0: after w1's ack the next lease from a DIFFERENT
    worker is an audit re-dispatch of the same unit; its matching ack
    closes the audit without disturbing the DONE result."""
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, audit_rate=1.0)
    g1 = _lease(coord, "w1")
    uid = g1["unit"]["unit_id"]
    _ack(coord, "w1", g1, attest=_at())
    g2 = _lease(coord, "w2")
    assert g2.get("audit") and g2["unit"]["unit_id"] == uid
    assert g2["checkpoint"] is None  # audits replay from scratch
    r = _ack(coord, "w2", g2, attest=_at(), audit=True)
    assert r["duplicate"]
    assert coord.counters["audits"] == 1
    assert coord.counters["audits_ok"] == 1
    assert coord.units[uid]["state"] == DONE
    assert coord.done
    coord.close(drained=False)


def test_attest_off_acks_carry_no_chain(tmp_path):
    """The chain fields must be absent byte-for-byte when attest is
    off — even a stray payload in the wire request is dropped."""
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, attest="off")
    g1 = _lease(coord, "w1")
    assert "attest" not in g1
    _ack(coord, "w1", g1, attest=_at())  # stray payload is DROPPED
    coord.close(drained=False)
    from primesim_tpu.analysis.fsck import _check_journal_dir

    root = str(tmp_path / "pool")
    records, _ = _check_journal_dir(root, root)
    acks = [x for x in records if x.get("t") == "ack"]
    assert acks and all("attest" not in x for x in acks)


def test_hedged_loser_ack_retained_even_attest_off(tmp_path):
    """Satellite: the losing twin's payload lands in the ledger as
    ack_dup regardless of attestation mode."""
    cfg, units = _units(1)
    coord = _coord(tmp_path, units, attest="off")
    g1 = _lease(coord, "w1")
    _ack(coord, "w1", g1)
    coord.handle({
        "verb": "ack", "worker": "w2",
        "unit_id": g1["unit"]["unit_id"], "epoch": g1["epoch"],
        "key": g1["unit"]["key"],
        "result": {"metric": "x", "value": 9}, "resumed_steps": 0,
    })
    coord.close(drained=False)
    from primesim_tpu.analysis.fsck import _check_journal_dir

    root = str(tmp_path / "pool")
    records, _ = _check_journal_dir(root, root)
    dups = [x for x in records if x.get("t") == "ack_dup"]
    assert len(dups) == 1
    assert dups[0]["worker"] == "w2"
    assert dups[0]["result"] == {"metric": "x", "value": 9}


# ---- fsck: attestation records -------------------------------------------


def test_fsck_attest_record_checks():
    from primesim_tpu.analysis.fsck import _check_attest_records

    good = _at()
    recs = [
        {"t": "ack", "unit_id": "u0", "attest": dict(good, head="zz")},
        {"t": "verdict", "unit_id": "u1", "outcome": "resolved",
         "attest": good},
        {"t": "ack", "unit_id": "u2", "attest": good},
        {"t": "suspect", "unit_id": "u2",
         "held": [{"worker": "w1", "attest": _at("b" * 64)}]},
        {"t": "audit", "unit_id": "u9", "worker": "w0", "ok": True},
    ]
    fs = _check_attest_records(recs, "pool", "/nonexistent", "/")
    details = " | ".join(f.detail for f in fs)
    assert len(fs) == 4 and all(f.corrupt for f in fs)
    assert "malformed chain payload" in details
    assert "no preceding suspect" in details
    assert "retained evidence was rewritten" in details
    assert "no acked result" in details
    # the legal stream raises nothing
    legal = [
        {"t": "ack", "unit_id": "u0", "attest": good},
        {"t": "suspect", "unit_id": "u0",
         "held": [{"worker": "w1", "attest": good},
                  {"worker": "w2", "attest": _at("b" * 64)}]},
        {"t": "verdict", "unit_id": "u0", "outcome": "resolved",
         "attest": good},
        {"t": "audit", "unit_id": "u0", "worker": "w3", "ok": True},
    ]
    assert _check_attest_records(legal, "pool", "/nonexistent", "/") == []


def _checkpointed_fleet(chunks=2):
    cfg, trace = _trace()
    fleet = FleetEngine(cfg, [trace], [{}], chunk_steps=16)
    fleet.attest = FleetAttest()
    fleet.attest.track(0, 16, start=0)
    for _ in range(chunks):
        fleet.step_chunk()
    return fleet


def test_fsck_ack_vs_checkpoint_agreement(tmp_path):
    """A surviving unit checkpoint whose chain contradicts the acked
    result is corrupt AND repairable: --repair quarantine moves the npz
    aside (the ledger, the truth, stays put)."""
    from primesim_tpu.analysis.fsck import run_fsck
    from primesim_tpu.sim.checkpoint import save_element_checkpoint

    fleet = _checkpointed_fleet()
    ck = fleet.attest.payload(0)
    assert ck["chunks"] == 2

    cfg, units = _units(1)
    root = str(tmp_path / "pool")
    coord = PoolCoordinator(units, root, lease_ttl_s=5.0, attest="chain")
    g = _lease(coord, "w1")
    uid = g["unit"]["unit_id"]
    # ack a chain the checkpoint does NOT prefix: same cadence, same
    # chunk count, different head
    _ack(coord, "w1", g, attest=dict(ck, head="f" * 64))
    coord.close(drained=False)
    ckpt = os.path.join(root, "units", f"{uid}.npz")
    os.makedirs(os.path.dirname(ckpt), exist_ok=True)
    save_element_checkpoint(ckpt, fleet, 0)

    res = run_fsck(root)
    hits = [f for f in res.findings if f.kind == "attest-checkpoint"]
    assert len(hits) == 1 and hits[0].corrupt and hits[0].repairable
    assert "disagrees with the acked result" in hits[0].detail
    res2 = run_fsck(root, repair="quarantine")
    assert any(q.endswith(f"{uid}.npz") for q in res2.quarantined)
    assert not os.path.exists(ckpt)
    ledger = os.path.join(root, "journal.jsonl")
    assert os.path.exists(ledger)  # the ledger is never moved


def test_fsck_clean_on_agreeing_checkpoint(tmp_path):
    from primesim_tpu.analysis.fsck import run_fsck
    from primesim_tpu.sim.checkpoint import save_element_checkpoint

    fleet = _checkpointed_fleet()
    ck = fleet.attest.payload(0)
    cfg, units = _units(1)
    root = str(tmp_path / "pool")
    coord = PoolCoordinator(units, root, lease_ttl_s=5.0, attest="chain")
    g = _lease(coord, "w1")
    uid = g["unit"]["unit_id"]
    _ack(coord, "w1", g, attest=ck)  # ack == checkpoint: a true prefix
    coord.close(drained=False)
    ckpt = os.path.join(root, "units", f"{uid}.npz")
    os.makedirs(os.path.dirname(ckpt), exist_ok=True)
    save_element_checkpoint(ckpt, fleet, 0)
    res = run_fsck(root)
    assert [f for f in res.findings if "attest" in f.kind] == []


# ---- offline audit (`primetpu audit`) ------------------------------------


@pytest.fixture(scope="module")
def drained_pool(tmp_path_factory):
    """One real attested pooled campaign, drained in-process; the
    module's offline-audit tests all read (never write) this ledger."""
    from primesim_tpu.chaos.campaign import _pool_drain

    root = str(tmp_path_factory.mktemp("audpool") / "pool")
    specs = [SYNTH.format(101), SYNTH.format(102)]
    results, counters, suspects = _pool_drain(
        root, _cfg(), specs, attest="chain", audit_rate=0.0, n_workers=1)
    assert all(r["state"] == "DONE" for r in results)
    assert not suspects
    return root


def test_offline_audit_confirms_clean_campaign(drained_pool):
    from primesim_tpu.attest.audit import run_audit

    out = run_audit(drained_pool)
    s = out["summary"]
    assert s["audited"] == 2 and s["ok"] == 2 and s["mismatch"] == 0
    for v in out["units"]:
        assert v["detail"]["ack"] == "confirmed"
        assert v["detail"]["replay"]["head"]


def test_offline_audit_flags_forged_ledger_head(drained_pool, tmp_path):
    """Rewrite one acked chain head (via a fresh, validly-framed ledger
    so the chain fsck stays green) -> the replay refuses to confirm."""
    import shutil

    from primesim_tpu.analysis.fsck import _check_journal_dir
    from primesim_tpu.attest.audit import run_audit
    from primesim_tpu.serve.journal import JobJournal

    root = str(tmp_path / "forged")
    shutil.copytree(drained_pool, root)
    records, _ = _check_journal_dir(root, root)
    for seg in os.listdir(root):
        if seg.startswith("journal"):
            os.unlink(os.path.join(root, seg))
    j = JobJournal(root)
    forged_uid = None
    for rec in records:
        if rec.get("t") == "ack" and forged_uid is None:
            rec = dict(rec)
            rec["attest"] = dict(rec["attest"], head="e" * 64)
            forged_uid = rec["unit_id"]
        j.append(rec)
    j.close()
    assert forged_uid is not None
    out = run_audit(root)
    assert out["summary"]["mismatch"] == 1
    bad = {v["unit_id"]: v for v in out["units"]}[forged_uid]
    assert bad["status"] == "mismatch"
    assert bad["detail"]["ack"]["journaled_head"] == "e" * 64


def test_offline_audit_survives_torn_ledger_tail(drained_pool, tmp_path):
    """kill -9 debris (a half-written final line) must neither crash the
    audit nor be repaired by it: the ledger bytes are evidence."""
    import shutil

    from primesim_tpu.attest.audit import run_audit

    root = str(tmp_path / "torn")
    shutil.copytree(drained_pool, root)
    active = os.path.join(root, "journal.jsonl")
    with open(active, "ab") as f:
        f.write(b'{"t":"ack","unit_id":"u9')  # torn mid-frame
    with open(active, "rb") as f:
        before = f.read()
    out = run_audit(root)
    assert out["summary"]["ok"] == 2
    with open(active, "rb") as f:
        assert f.read() == before


def test_offline_audit_selects_units_and_rejects_unknown(drained_pool):
    from primesim_tpu.attest.audit import run_audit

    out = run_audit(drained_pool, unit_ids=["u00001"])
    assert [v["unit_id"] for v in out["units"]] == ["u00001"]
    with pytest.raises(AttestationError) as ei:
        run_audit(drained_pool, unit_ids=["nope"])
    assert ei.value.location()["site"] == "audit.ledger"


@pytest.mark.slow  # slow: subprocess CLI; integrity-chaos CI job runs it
def test_cli_audit_verb_exit_contract(drained_pool):
    """`primetpu audit` on a clean pool: one JSON verdict line per unit,
    exit 0; on a missing dir: the typed error contract on exit 2."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "primesim_tpu.cli", "audit", drained_pool],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
    )
    assert p.returncode == 0, p.stderr
    lines = [json.loads(ln) for ln in p.stdout.splitlines() if ln.strip()]
    assert {v["unit_id"] for v in lines} == {"u00000", "u00001"}
    assert all(v["status"] == "ok" for v in lines)

    p2 = subprocess.run(
        [sys.executable, "-m", "primesim_tpu.cli", "audit",
         drained_pool + "-nope"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert p2.returncode == 2
    err = json.loads(p2.stderr.splitlines()[-1])
    assert err["error"]["type"] == "AttestationError"
    assert err["error"]["location"]["site"] == "audit.ledger"


# ---- chaos: silent corruption vs invariant F -----------------------------


@pytest.fixture(autouse=True)
def _no_leftover_runtime():
    from primesim_tpu.chaos import sites

    sites.deactivate()
    yield
    sites.deactivate()


def _flip(site, occ, **args):
    from primesim_tpu.chaos import plan as P

    return P.FaultEvent(site=site, occurrence=occ, action="flip",
                        args=tuple(sorted(args.items())))


@pytest.mark.slow  # slow: pooled chaos trial; integrity-chaos CI job runs it
def test_silent_corruption_trial_invariant_f(tmp_path):
    """A flipped committed counter mid-campaign: invariant F — no
    corrupted result reaches DONE unflagged — must hold, and the trial
    must actually have injected the flip it claims to test."""
    from primesim_tpu.chaos import campaign as C
    from primesim_tpu.chaos import plan as P

    plan = P.FaultPlan(seed=11, events=(
        _flip("fleet.counters", 1),
    ))
    res = C.run_attest_trial(plan, workdir=str(tmp_path))
    assert res.ok, res.violations
    assert any(e["site"] == "fleet.counters" for e in res.injected)


@pytest.mark.slow  # slow: pooled chaos trial; integrity-chaos CI job runs it
def test_silent_corruption_clean_plan_zero_false_positives(tmp_path):
    """The dual: a trial where no flip fires must end with every unit
    DONE, zero mismatches, zero SUSPECTs, zero quarantined workers."""
    from primesim_tpu.chaos import campaign as C
    from primesim_tpu.chaos import plan as P

    res = C.run_attest_trial(P.FaultPlan(seed=12, events=()),
                             workdir=str(tmp_path))
    assert res.ok, res.violations
    assert res.injected == []


@pytest.mark.slow
def test_silent_corruption_seeded_campaign(tmp_path):
    """CI shape: a seeded silent_corruption campaign where every flip
    that fires is flagged and no clean trial raises a false positive."""
    from primesim_tpu.chaos import campaign as C

    rep = C.run_campaign(n_trials=6, seed0=2026,
                         classes=("silent_corruption",),
                         workdir=str(tmp_path))
    assert rep["ok"], rep["violations"]
    assert rep["trials"] == 6


@pytest.mark.slow
def test_offline_audit_after_kill9_campaign(tmp_path):
    """SIGKILL the whole pooled campaign mid-flight, then audit the
    surviving ledger offline: DONE units replay and confirm, in-flight
    units are skipped, nothing crashes, nothing is mutated."""
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        f.write(_cfg().to_json())
    pool = str(tmp_path / "pool")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "primesim_tpu.cli", "sweep", cfg_path,
         "--synth", SYNTH.format(1), "--synth", SYNTH.format(2),
         "--synth", SYNTH.format(3), "--synth", SYNTH.format(4),
         "--workers", "1", "--pool-dir", pool, "--attest", "chain",
         "--chunk-steps", "16", "--max-steps", "100000", "--hedge",
         "off"],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 240
    done = 0
    try:
        while time.time() < deadline:
            time.sleep(0.5)
            if proc.poll() is not None:
                break
            try:
                from primesim_tpu.analysis.fsck import _check_journal_dir

                records, _ = _check_journal_dir(pool, pool)
                done = sum(1 for r in records if r.get("t") == "ack")
            except Exception:
                continue
            if done >= 1:
                os.killpg(proc.pid, signal.SIGKILL)
                break
    finally:
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    assert done >= 1, "campaign never acked a unit before the deadline"

    from primesim_tpu.attest.audit import run_audit

    out = run_audit(pool)
    s = out["summary"]
    assert s["mismatch"] == 0
    assert s["ok"] >= done
